"""Model promotion & freshness (ISSUE 19): the embedding-space
compatibility scorer (a rotated/skewed candidate is rejected with the
gate named in the ledger), freshness burn-rate math + window eviction
and the index row-age stamps behind it, the staged-rollout state
machine including auto-rollback on a burn breach, the append-only
audit-ledger schema, and the router's version-skew gauge +
/admin/promote endpoint."""

import json
import os
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from moco_tpu.obs import quality, schema
from moco_tpu.obs.slo import FreshnessBurnTracker, fresh_alert_spec
from moco_tpu.serve.index import EmbeddingIndex
from moco_tpu.serve.promote import (
    PromotionLedger,
    StagedRollout,
    ledger_record,
    run_gate_battery,
)


# -- fakes ---------------------------------------------------------------


class LinearEngine:
    """Engine-shaped fake: flattens the probe images, projects through a
    fixed matrix, L2-normalizes — so two engines sharing a matrix are
    'compatible' and a rotated matrix is a skewed checkpoint."""

    def __init__(self, mat: np.ndarray):
        self.mat = np.asarray(mat, np.float32)

    def embed(self, images):
        x = np.asarray(images, np.float32).reshape(images.shape[0], -1)
        x = x[:, : self.mat.shape[0]]
        e = x @ self.mat
        e /= np.linalg.norm(e, axis=1, keepdims=True) + 1e-9
        return e.astype(np.float32), [(images.shape[0], images.shape[0])]


def _engines(dim=8, rotate=False, seed=0):
    rng = np.random.RandomState(seed)
    base = rng.randn(dim, dim).astype(np.float32)
    live = LinearEngine(base)
    if rotate:
        q, _ = np.linalg.qr(rng.randn(dim, dim))
        cand = LinearEngine(base @ q.astype(np.float32))
    else:
        cand = LinearEngine(base + 0.005 * rng.randn(dim, dim).astype(np.float32))
    return live, cand


def _live_index(dim=8, rows=64, seed=1):
    rng = np.random.RandomState(seed)
    emb = rng.randn(rows, dim).astype(np.float32)
    emb /= np.linalg.norm(emb, axis=1, keepdims=True)
    idx = EmbeddingIndex(dim=dim, capacity=rows)
    idx.snapshot(emb, now=0.0)
    return idx


# -- compatibility scorer ------------------------------------------------


def test_params_digest_stable_and_content_sensitive():
    params = {"backbone": {"w": np.arange(6.0).reshape(2, 3)}, "head": {"b": np.ones(3)}}
    same = {"head": {"b": np.ones(3)}, "backbone": {"w": np.arange(6.0).reshape(2, 3)}}
    assert quality.params_digest(params) == quality.params_digest(same)
    bumped = {"backbone": {"w": np.arange(6.0).reshape(2, 3) + 1e-6}, "head": {"b": np.ones(3)}}
    assert quality.params_digest(params) != quality.params_digest(bumped)
    # shape/dtype changes disagree even when bytes could collide
    reshaped = {"backbone": {"w": np.arange(6.0).reshape(3, 2)}, "head": {"b": np.ones(3)}}
    assert quality.params_digest(params) != quality.params_digest(reshaped)


def test_compat_cosine_identity_vs_rotation():
    live, cand = _engines(rotate=False)
    probes = quality.synthetic_probes(16, 4)
    a, _ = live.embed(probes)
    b, _ = cand.embed(probes)
    assert quality.compat_cosine(a, a) == pytest.approx(1.0, abs=1e-5)
    assert quality.compat_cosine(a, b) > 0.95
    live, rot = _engines(rotate=True)
    r, _ = rot.embed(probes)
    assert quality.compat_cosine(a, r) < 0.8
    with pytest.raises(ValueError):
        quality.compat_cosine(a, a[:-1])


def test_recall_overlap_identity_is_one_rotation_is_not():
    live, _ = _engines()
    _, rot = _engines(rotate=True)
    idx = _live_index()
    probes = quality.synthetic_probes(16, 4)
    a, _ = live.embed(probes)
    r, _ = rot.embed(probes)
    assert quality.recall_overlap(a, a, idx, k=5) == pytest.approx(1.0)
    assert quality.recall_overlap(a, r, idx, k=5) < 0.5
    with pytest.raises(ValueError):
        quality.recall_overlap(a, a, EmbeddingIndex(dim=8, capacity=4))


def test_synthetic_probes_deterministic_uint8():
    a = quality.synthetic_probes(8, 16, seed=3)
    b = quality.synthetic_probes(8, 16, seed=3)
    assert a.dtype == np.uint8 and a.shape == (8, 16, 16, 3)
    assert np.array_equal(a, b)
    assert not np.array_equal(a, quality.synthetic_probes(8, 16, seed=4))


def test_model_and_compat_payloads_are_schema_valid():
    line = {"step": 0, "time": 1.0}
    line.update(quality.model_payload(7, "abc123"))
    line.update(quality.compat_payload(0.98, 0.9))
    assert schema.validate_line(line) == []
    line.update(quality.model_payload(None, None))
    line.update(quality.compat_payload(None, None))
    assert schema.validate_line(line) == []
    bad = {"step": 0, "time": 1.0, "serve/compat_cosine": 1.5}
    assert schema.validate_line(bad)


# -- gate battery --------------------------------------------------------


def test_gate_battery_accepts_compatible_candidate():
    live, cand = _engines()
    res = run_gate_battery(live, cand, quality.synthetic_probes(16, 4),
                           index=_live_index(), k=5)
    assert res["ok"] and res["failed_gate"] is None
    assert set(res["gates"]) >= {"compat_cosine", "recall_overlap", "feature_std"}
    assert all(g["ok"] for g in res["gates"].values())
    assert schema.validate_line({"step": 0, "time": 1.0, **res["compat"]}) == []


def test_gate_battery_rejects_rotated_checkpoint_naming_the_gate():
    live, rot = _engines(rotate=True)
    res = run_gate_battery(live, rot, quality.synthetic_probes(16, 4),
                           index=_live_index(), k=5)
    assert not res["ok"]
    # the FIRST failing gate is named — the ledger line carries it
    assert res["failed_gate"] == "compat_cosine"
    assert not res["gates"]["compat_cosine"]["ok"]
    assert res["gates"]["compat_cosine"]["value"] < res["gates"]["compat_cosine"]["floor"]


def test_gate_battery_catches_dimensional_collapse():
    live, _ = _engines()

    class Collapsed:
        def embed(self, images):
            e = np.tile(np.eye(1, 8, dtype=np.float32), (images.shape[0], 1))
            return e, [(images.shape[0], images.shape[0])]

    res = run_gate_battery(
        live, Collapsed(), quality.synthetic_probes(16, 4),
        # a collapsed embedding keeps cosine with nothing pinned; gate
        # only the collapse detector so the failure attribution is exact
        floors={"compat_cosine": -1.0},
    )
    assert not res["ok"] and res["failed_gate"] == "feature_std"


def test_gate_battery_ema_drift_ceiling():
    live, cand = _engines()
    probes = quality.synthetic_probes(8, 4)
    pq = {"backbone": {"w": np.ones((3, 3), np.float32)}}
    pk_close = {"backbone": {"w": np.ones((3, 3), np.float32) * 1.001}}
    pk_torn = {"backbone": {"w": -np.ones((3, 3), np.float32)}}
    ok = run_gate_battery(live, cand, probes, cand_params_q=pq, cand_params_k=pk_close)
    assert ok["gates"]["ema_drift_max"]["ok"]
    torn = run_gate_battery(live, cand, probes, cand_params_q=pq, cand_params_k=pk_torn)
    assert not torn["gates"]["ema_drift_max"]["ok"]
    assert torn["failed_gate"] == "ema_drift_max"


def test_gate_battery_live_recall_floor_is_opt_in():
    live, cand = _engines()
    probes = quality.synthetic_probes(8, 4)
    res = run_gate_battery(live, cand, probes, live_recall=0.2)
    assert "live_recall" not in res["gates"]  # no floor declared
    res = run_gate_battery(live, cand, probes,
                           floors={"live_recall": 0.5}, live_recall=0.2)
    assert res["failed_gate"] == "live_recall"


# -- audit ledger --------------------------------------------------------


def test_ledger_lines_are_schema_strict_and_append_only(tmp_path):
    led = PromotionLedger(os.path.join(tmp_path, "promotions.jsonl"))
    live, rot = _engines(rotate=True)
    res = run_gate_battery(live, rot, quality.synthetic_probes(16, 4),
                           index=_live_index(), k=5)
    led.append(ledger_record(3, "rejected", "gates", digest="d3",
                             failed_gate=res["failed_gate"],
                             gates=res["gates"], compat=res["compat"]))
    led.append(ledger_record(4, "accepted", "gates", digest="d4"))
    led.append(ledger_record(4, "promoted", "rollout", digest="d4"))
    recs = led.read()
    assert [r["promotion/verdict"] for r in recs] == [
        "rejected", "accepted", "promoted",
    ]
    # the rejected line names the killing gate and carries its evidence
    assert recs[0]["promotion/failed_gate"] == "compat_cosine"
    assert recs[0]["promotion/gate/compat_cosine"] < recs[0]["promotion/floor/compat_cosine"]
    assert recs[0]["promotion/gate_ok/compat_cosine"] == 0
    assert recs[0]["event"] == "promotion"
    # every line on disk passes the strict schema independently
    with open(led.path) as f:
        assert schema.validate_lines(f) == []


def test_ledger_rejects_unschemad_records(tmp_path):
    led = PromotionLedger(os.path.join(tmp_path, "promotions.jsonl"))
    with pytest.raises(ValueError):
        ledger_record(1, "shipped", "gates")  # unknown verdict
    rec = ledger_record(1, "accepted", "gates")
    del rec["time"]  # schema requires step+time
    with pytest.raises(ValueError):
        led.append(rec)
    rec2 = ledger_record(1, "accepted", "gates")
    rec2["promotion/gate/compat_cosine"] = float("nan")
    with pytest.raises(ValueError):
        led.append(rec2)  # allow_nan=False: a NaN never lands on disk
    assert led.read() == []  # nothing landed


# -- freshness SLO -------------------------------------------------------


def test_fresh_burn_math_and_window_eviction():
    t = FreshnessBurnTracker(max_age_s=5.0, objective=0.9, windows=(10, 100))
    for i in range(10):
        t.record(2.0, now=1000 + i)  # fresh
    assert t.burn_rates(now=1009)[10] == pytest.approx(0.0)
    for i in range(10):
        t.record(60.0, now=1010 + i)  # stale: every observation burns
    rates = t.burn_rates(now=1019)
    assert rates[10] == pytest.approx(1.0 / 0.1, rel=1e-6)  # 100% bad / 10% budget
    assert rates[100] == pytest.approx(0.5 / 0.1, rel=1e-6)  # half the window bad
    # eviction: past the long window the old buckets are gone
    t.record(2.0, now=1500)
    assert t.burn_rates(now=1500)[100] == pytest.approx(0.0)
    # an empty index (no stamped rows) is not stale; a silent window is None
    t2 = FreshnessBurnTracker(max_age_s=5.0, windows=(10,))
    t2.record(None, now=0)
    assert t2.burn_rates(now=0)[10] == pytest.approx(0.0)
    assert t2.burn_rates(now=100)[10] is None
    with pytest.raises(ValueError):
        FreshnessBurnTracker(max_age_s=0.0)


def test_fresh_payload_and_alert_spec():
    t = FreshnessBurnTracker(max_age_s=3.0, windows=(10, 60))
    t.record(10.0, now=100)
    p = t.payload(now=100)
    assert p["serve/fresh_max_age_s"] == 3.0
    assert p["serve/fresh_burn_rate_10s"] > 0
    assert schema.validate_line({"step": 0, "time": 1.0, **p}) == []
    spec = fresh_alert_spec(windows=(10, 60))
    assert "name=fresh_burn_fast:field=serve/fresh_burn_rate_10s" in spec
    assert "name=fresh_burn_slow:field=serve/fresh_burn_rate_60s" in spec
    from moco_tpu.obs import alerts

    assert len(alerts.parse_rules(spec)) == 2


def test_index_row_age_stamps_follow_snapshot_and_add():
    idx = EmbeddingIndex(dim=4, capacity=8)
    assert idx.row_age_stats(now=10.0) == {
        "row_age_max_s": None, "row_age_mean_s": None,
    }
    rows = np.eye(4, dtype=np.float32)
    idx.snapshot(rows, now=100.0)
    st = idx.row_age_stats(now=130.0)
    assert st["row_age_max_s"] == pytest.approx(30.0)
    assert st["row_age_mean_s"] == pytest.approx(30.0)
    # a fresh ingest stamps exactly the rows it wrote (FIFO append here)
    idx.add(rows[:2], now=128.0)
    st = idx.row_age_stats(now=130.0)
    assert idx.count == 6
    assert st["row_age_max_s"] == pytest.approx(30.0)
    assert st["row_age_mean_s"] == pytest.approx((30.0 * 4 + 2.0 * 2) / 6)
    # wrap-around overwrites re-stamp the overwritten slots
    idx.add(np.tile(rows, (1, 1))[:4], now=129.0)  # fills 6,7 then wraps to 0,1
    st = idx.row_age_stats(now=130.0)
    assert idx.count == 8
    assert st["row_age_max_s"] == pytest.approx(30.0)  # rows 2,3 still old
    assert st["row_age_mean_s"] == pytest.approx(
        (30.0 * 2 + 2.0 * 2 + 1.0 * 4) / 8
    )
    # ages clamp at zero (a clock hiccup never reports negative age)
    assert idx.row_age_stats(now=0.0)["row_age_max_s"] == 0.0


# -- staged rollout ------------------------------------------------------


class _Fleet:
    """Swap/status/burn fakes with a deterministic clock: a swap takes
    `swap_lag_polls` sleep ticks to land, like a real drain/restart."""

    def __init__(self, n=3, swap_lag_polls=1):
        self.n = n
        self.digest = {i: "old" for i in range(n)}
        self.pending: dict = {}  # replica -> [polls_left, target_digest]
        self.swap_lag_polls = swap_lag_polls
        self.swaps: list = []
        self.backs: list = []
        self.t = 0.0
        self.burn_value = 0.0

    def clock(self):
        return self.t

    def sleep(self, s):
        self.t += s
        for i in list(self.pending):
            self.pending[i][0] -= 1
            if self.pending[i][0] <= 0:
                self.digest[i] = self.pending.pop(i)[1]

    def swap(self, i):
        self.swaps.append(i)
        self.pending[i] = [self.swap_lag_polls, "new"]

    def swap_back(self, i):
        self.backs.append(i)
        self.pending[i] = [self.swap_lag_polls, "old"]

    def status(self, i):
        return {
            "healthy": True, "draining": i in self.pending,
            "drain_phase": "restarting" if i in self.pending else None,
            "model_digest": self.digest[i],
        }

    def burn(self):
        return self.burn_value


def test_rollout_promotes_one_replica_at_a_time():
    f = _Fleet(n=3)
    out = StagedRollout(
        3, f.swap, f.status, burn=f.burn, swap_back=f.swap_back,
        target_digest="new", soak_s=0.5, poll_s=0.1,
        sleep=f.sleep, clock=f.clock,
    ).run()
    assert out["verdict"] == "promoted" and out["swapped"] == [0, 1, 2]
    assert f.swaps == [0, 1, 2] and f.backs == []
    assert all(d == "new" for d in f.digest.values())


def test_rollout_burn_breach_rolls_everything_back():
    f = _Fleet(n=3)

    def burn_after_second_swap():
        # the fleet sours once the candidate reaches replica 1
        return 99.0 if f.digest[1] == "new" else 0.2

    out = StagedRollout(
        3, f.swap, f.status, burn=burn_after_second_swap,
        swap_back=f.swap_back, target_digest="new", soak_s=0.5, poll_s=0.1,
        sleep=f.sleep, clock=f.clock, burn_ceiling=14.4,
    ).run()
    assert out["verdict"] == "rolled_back"
    assert out["reason"] == "burn_breach" and out["burn"] == 99.0
    assert out["replica"] == 1 and out["swapped"] == [0, 1]
    # every touched replica went back, replica 2 never swapped
    assert f.backs == [0, 1] and f.swaps == [0, 1]
    assert f.digest == {0: "old", 1: "old", 2: "old"}


def test_rollout_swap_timeout_rolls_back():
    f = _Fleet(n=2)

    def never_lands(i):
        f.swaps.append(i)  # the swap starts but the digest never flips

    out = StagedRollout(
        2, never_lands, f.status, burn=f.burn, swap_back=f.swap_back,
        target_digest="new", soak_s=0.1, swap_timeout_s=1.0, poll_s=0.2,
        sleep=f.sleep, clock=f.clock,
    ).run()
    assert out["verdict"] == "rolled_back" and out["reason"] == "swap_timeout"
    assert out["replica"] == 0 and out["swapped"] == []
    assert f.backs == [0]  # the half-swapped replica is still reverted


def test_rollout_none_burn_is_not_a_breach():
    f = _Fleet(n=1)
    out = StagedRollout(
        1, f.swap, f.status, burn=lambda: None, swap_back=f.swap_back,
        target_digest="new", soak_s=0.3, poll_s=0.1,
        sleep=f.sleep, clock=f.clock,
    ).run()
    assert out["verdict"] == "promoted"


# -- router: version skew + /admin/promote -------------------------------


def _wait(pred, timeout=5.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(0.05)
    return pred()


def test_router_model_skew_and_fresh_burn_aggregates():
    from moco_tpu.serve.router import FleetRouter
    from tests.test_router import FakeReplica

    fakes = [FakeReplica(0), FakeReplica(1)]
    fakes[0].set(stats_extra={
        "serve/model_step": 5, "serve/model_digest": "aaa",
        "serve/fresh_burn_rate_60s": 0.5,
    })
    fakes[1].set(stats_extra={
        "serve/model_step": 7, "serve/model_digest": "bbb",
        "serve/fresh_burn_rate_60s": 1.5,
    })
    router = FleetRouter(
        replica_urls=[f.url for f in fakes], slo_ms=1000.0,
        health_interval_s=0.1,
    )
    try:
        assert _wait(lambda: router.stats()["fleet_serve/model_skew"] == 1)
        st = router.stats()
        assert st["fleet_serve/fresh_burn_rate_60s_max"] == pytest.approx(1.5)
        assert st["fleet_serve/fresh_burn_rate_60s_min"] == pytest.approx(0.5)
        assert st["fleet_serve/fresh_burn_rate_60s_mean"] == pytest.approx(1.0)
        # /admin/replicas snapshots carry the served version per replica
        with urllib.request.urlopen(
            f"http://{router.host}:{router.port}/admin/replicas", timeout=5
        ) as r:
            snaps = json.loads(r.read())["replicas"]
        assert {s["model_digest"] for s in snaps} == {"aaa", "bbb"}
        assert {s["model_step"] for s in snaps} == {5, 7}
        # skew heals when the fleet converges
        fakes[1].set(stats_extra={
            "serve/model_step": 5, "serve/model_digest": "aaa",
        })
        assert _wait(lambda: router.stats()["fleet_serve/model_skew"] == 0)
    finally:
        router.close()
        for f in fakes:
            f.close()


def test_router_admin_promote_requires_supervisor_then_swaps():
    from moco_tpu.serve.router import FleetRouter
    from tests.test_router import FakeReplica

    fakes = [FakeReplica(0), FakeReplica(1)]

    class FakeSupervisor:
        def __init__(self):
            self.ckpt_dirs: list = []
            self.restarts: list = []

        def set_ckpt_dir(self, path):
            self.ckpt_dirs.append(path)

        def restart_replica(self, index):
            self.restarts.append(index)

    def _promote(router, i, ckpt="/run/candidate dir"):
        from urllib.parse import quote

        req = urllib.request.Request(
            f"http://{router.host}:{router.port}"
            f"/admin/promote?replica={i}&ckpt_dir={quote(ckpt, safe='')}",
            data=b"",
        )
        with urllib.request.urlopen(req, timeout=10) as r:
            return r.status, json.loads(r.read())

    bare = FleetRouter(
        replica_urls=[f.url for f in fakes], slo_ms=1000.0,
        health_interval_s=0.1,
    )
    try:
        with pytest.raises(urllib.error.HTTPError) as e:
            _promote(bare, 0)
        assert e.value.code == 409  # no supervisor: promotion refused
    finally:
        bare.close()

    sup = FakeSupervisor()
    router = FleetRouter(
        replica_urls=[f.url for f in fakes], slo_ms=1000.0,
        health_interval_s=0.1, supervisor=sup,
    )
    try:
        status, body = _promote(router, 1)
        assert status == 202 and body["accepted"]
        # the swap retargeted the supervisor (percent-decoded) and the
        # drain worker restarted exactly that replica through it
        assert sup.ckpt_dirs == ["/run/candidate dir"]
        assert _wait(lambda: sup.restarts == [1])
        assert _wait(
            lambda: router.stats()["fleet_serve/replicas_healthy"] == 2
        )
        # bad requests are 400s, not silent no-ops
        for q in ("replica=1", "ckpt_dir=/x", "replica=9&ckpt_dir=/x"):
            req = urllib.request.Request(
                f"http://{router.host}:{router.port}/admin/promote?" + q,
                data=b"",
            )
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(req, timeout=10)
            assert e.value.code == 400
    finally:
        router.close()
        for f in fakes:
            f.close()
