"""Linear probe: surgery, frozen-backbone training, sanity check, eval.

Reference invariants under test (`main_lincls.py`, SURVEY.md §3.2):
- checkpoint surgery keeps the query backbone only;
- only fc trains — backbone bit-identical afterwards (sanity_check);
- eval-mode BN during probe training (running stats never move);
- top-1/5 validation runs and best-acc snapshotting works.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from moco_tpu.data.datasets import SyntheticDataset
from moco_tpu.utils.config import DataConfig, MocoConfig, OptimConfig, ProbeConfig, TrainConfig


@pytest.fixture(scope="module")
def pretrained(tmp_path_factory):
    """A 1-epoch pretrain run to produce a real checkpoint to probe."""
    from moco_tpu.train import train

    workdir = tmp_path_factory.mktemp("pre")
    config = TrainConfig(
        moco=MocoConfig(
            arch="resnet18", dim=16, num_negatives=32, mlp=True,
            shuffle="gather_perm", cifar_stem=True, compute_dtype="float32",
        ),
        optim=OptimConfig(lr=0.03, epochs=1, cos=True),
        data=DataConfig(dataset="synthetic", image_size=16, global_batch=16, num_workers=2),
        workdir=str(workdir),
        log_every=100,
    )
    dataset = SyntheticDataset(num_examples=32, image_size=16)
    train(config, dataset=dataset)
    return config


def test_surgery_extracts_backbone(pretrained):
    from moco_tpu.lincls import load_pretrained_backbone

    params, stats, cfg = load_pretrained_backbone(pretrained.workdir, pretrained)
    # backbone params only — no projection-head keys
    assert all("Dense" not in k for k in params)
    assert jax.tree.leaves(params)


def test_surgery_reads_config_from_checkpoint(pretrained):
    """With config=None the checkpointed config rebuilds the template."""
    from moco_tpu.lincls import load_pretrained_backbone

    params, stats, cfg = load_pretrained_backbone(pretrained.workdir)
    assert cfg.moco.arch == pretrained.moco.arch
    assert cfg.optim.optimizer == pretrained.optim.optimizer
    assert jax.tree.leaves(params)


def test_probe_trains_fc_only_and_sanity_checks(tmp_path, pretrained):
    from moco_tpu.lincls import sanity_check, train_lincls

    probe = ProbeConfig(lr=1.0, epochs=2, schedule=(60, 80), num_classes=10)
    data = dataclasses.replace(pretrained.data, global_batch=16)
    train_ds = SyntheticDataset(num_examples=32, image_size=16)
    val_ds = SyntheticDataset(num_examples=16, image_size=16)
    result = train_lincls(
        pretrained.workdir,
        probe,
        pretrain_config=pretrained,
        data=data,
        workdir=str(tmp_path / "probe"),
        train_dataset=train_ds,
        val_dataset=val_ds,
        log_every=100,
    )
    assert np.isfinite(result["loss"])
    assert 0.0 <= result["best_acc1"] <= 100.0
    assert "acc5" in result


def test_sanity_check_catches_mutation(pretrained):
    from moco_tpu.lincls import ProbeState, load_pretrained_backbone, sanity_check

    params, stats, _ = load_pretrained_backbone(pretrained.workdir, pretrained)
    state = ProbeState(
        step=jnp.zeros((), jnp.int32),
        fc_params={},
        backbone_params=jax.tree.map(lambda x: x + 1e-3, params),
        backbone_stats=stats,
        opt_state=(),
    )
    with pytest.raises(AssertionError, match="backbone weight changed"):
        sanity_check(state, params)


def test_probe_step_is_eval_mode(pretrained):
    """BN running stats must not move during probe training: feed two very
    different batches; outputs must depend only on frozen stats."""
    from moco_tpu.lincls import _build_probe_model, load_pretrained_backbone

    params, stats, _ = load_pretrained_backbone(pretrained.workdir, pretrained)
    backbone, _ = _build_probe_model(pretrained, num_classes=10)
    x1 = jnp.ones((4, 16, 16, 3), jnp.float32)
    out1 = backbone.apply({"params": params, "batch_stats": stats}, x1, train=False)
    # eval-mode apply without mutable batch_stats cannot update stats;
    # applying twice must be deterministic
    out2 = backbone.apply({"params": params, "batch_stats": stats}, x1, train=False)
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))


def test_evaluate_only_mode(tmp_path, pretrained):
    """`--evaluate` parity (main_lincls.py): validation-only on the
    saved model_best must reproduce the probe's best val accuracy."""
    from moco_tpu.lincls import evaluate_lincls, train_lincls

    probe = ProbeConfig(num_classes=10, lr=0.5, epochs=2, schedule=(1, 2))
    data = dataclasses.replace(pretrained.data)
    workdir = str(tmp_path / "probe")
    train_ds = SyntheticDataset(num_examples=32, image_size=16)
    val_ds = SyntheticDataset(num_examples=32, image_size=16)
    out = train_lincls(
        pretrained.workdir, probe, data=data, workdir=workdir,
        train_dataset=train_ds, val_dataset=val_ds,
    )
    # caller flags deliberately WRONG for every template-shaping field
    # (the checkpoint's own saved probe config must win: wd/momentum
    # shape the opt-state tree, num_classes the fc kernel), AND a
    # nonsense pretrain workdir: the probe checkpoint alone suffices
    # data=None: the probe checkpoint's SAVED data config must drive the
    # eval pipeline (not the caller, not the pretrain default)
    wrong = ProbeConfig(num_classes=77, lr=9.9, momentum=0.0, weight_decay=0.5, epochs=1)
    ev = evaluate_lincls(
        str(tmp_path / "no_such_pretrain"), wrong,
        workdir=workdir, val_dataset=val_ds,
    )
    assert ev["acc1"] == pytest.approx(out["best_acc1"], abs=1e-6)
    assert ev["count"] == 32
