"""Model-family tests: shapes, parameter counts vs torchvision, BN modes."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from moco_tpu.models import create_resnet, ProjectionHead, LinearClassifier


def n_params(tree):
    return sum(np.prod(x.shape) for x in jax.tree.leaves(tree))


# torchvision backbone param counts (fc excluded), ground truth from
# torchvision.models.resnet*(num_classes=...) minus fc params.
TORCHVISION_BACKBONE_PARAMS = {
    "resnet18": 11_176_512,
    "resnet50": 23_508_032,
}


@pytest.mark.parametrize("arch", ["resnet18", "resnet50"])
def test_param_count_matches_torchvision(arch):
    model = create_resnet(arch)
    variables = model.init(jax.random.key(0), jnp.zeros((1, 32, 32, 3)), train=False)
    got = n_params(variables["params"])
    assert got == TORCHVISION_BACKBONE_PARAMS[arch], (arch, got)


def test_forward_shapes_and_features():
    model = create_resnet("resnet18", cifar_stem=True)
    variables = model.init(jax.random.key(0), jnp.zeros((2, 32, 32, 3)), train=False)
    out = model.apply(variables, jnp.ones((2, 32, 32, 3)), train=False)
    assert out.shape == (2, 512)
    assert model.num_features == 512
    assert create_resnet("resnet50").num_features == 2048


def test_train_mode_updates_batch_stats():
    model = create_resnet("resnet18", cifar_stem=True)
    x = jax.random.normal(jax.random.key(1), (4, 16, 16, 3))
    variables = model.init(jax.random.key(0), x, train=False)
    _, mutated = model.apply(variables, x, train=True, mutable=["batch_stats"])
    before = jax.tree.leaves(variables["batch_stats"])
    after = jax.tree.leaves(mutated["batch_stats"])
    assert any(not np.allclose(b, a) for b, a in zip(before, after))


def test_eval_mode_is_deterministic_wrt_batch():
    """Eval BN must use running stats: per-sample output independent of
    batch composition."""
    model = create_resnet("resnet18", cifar_stem=True)
    x = jax.random.normal(jax.random.key(1), (4, 16, 16, 3))
    variables = model.init(jax.random.key(0), x, train=False)
    full = model.apply(variables, x, train=False)
    half = model.apply(variables, x[:2], train=False)
    np.testing.assert_allclose(full[:2], half, rtol=1e-3, atol=1e-5)


def test_bf16_compute_fp32_out():
    model = create_resnet("resnet18", cifar_stem=True, dtype=jnp.bfloat16)
    x = jnp.ones((2, 16, 16, 3))
    variables = model.init(jax.random.key(0), x, train=False)
    out = model.apply(variables, x, train=False)
    assert out.dtype == jnp.float32
    # params stay fp32 (param_dtype default)
    assert all(p.dtype == jnp.float32 for p in jax.tree.leaves(variables["params"]))


def test_projection_heads():
    feats = jnp.ones((2, 512))
    for mlp, expect_params in [(False, 512 * 128 + 128), (True, 512 * 512 + 512 + 512 * 128 + 128)]:
        head = ProjectionHead(dim=128, mlp=mlp)
        v = head.init(jax.random.key(0), feats)
        assert head.apply(v, feats).shape == (2, 128)
        assert n_params(v["params"]) == expect_params


def test_linear_classifier_init():
    head = LinearClassifier(num_classes=10)
    v = head.init(jax.random.key(0), jnp.ones((2, 512)))
    k = v["params"]["Dense_0"]["kernel"]
    assert np.abs(k).std() < 0.02 and not np.allclose(k, 0)
    assert np.allclose(v["params"]["Dense_0"]["bias"], 0)


class TestSubsetStatsBatchNorm:
    """The byte-reduction BN (PROFILE.md lever): statistics from the
    first `stats_rows` rows, normalization over all rows, tree paths
    identical to nn.BatchNorm so checkpoints interchange."""

    def _mods(self, stats_rows):
        import flax.linen as nn

        from moco_tpu.models.resnet import BatchNorm

        ours = BatchNorm(stats_rows=stats_rows, use_running_average=False)
        ref = nn.BatchNorm(use_running_average=False, momentum=0.9, epsilon=1e-5)
        return ours, ref

    def test_full_batch_matches_flax_batchnorm(self):
        ours, ref = self._mods(stats_rows=0)
        x = jax.random.normal(jax.random.PRNGKey(0), (8, 4, 4, 6))
        vo = ours.init(jax.random.PRNGKey(1), x)
        vr = ref.init(jax.random.PRNGKey(1), x)
        yo, mo = ours.apply(vo, x, mutable=["batch_stats"])
        yr, mr = ref.apply(vr, x, mutable=["batch_stats"])
        np.testing.assert_allclose(np.asarray(yo), np.asarray(yr), atol=1e-5)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, atol=1e-5),
            mo["batch_stats"], mr["batch_stats"],
        )

    def test_subset_stats_are_first_rows_only(self):
        ours, _ = self._mods(stats_rows=4)
        x = jax.random.normal(jax.random.PRNGKey(0), (16, 3, 3, 5))
        v = ours.init(jax.random.PRNGKey(1), x)
        y, mut = ours.apply(v, x, mutable=["batch_stats"])
        sub = np.asarray(x[:4], np.float64)
        mean = sub.mean(axis=(0, 1, 2))
        var = (sub**2).mean(axis=(0, 1, 2)) - mean**2
        # normalization over ALL rows with the subset statistics
        expect = (np.asarray(x, np.float64) - mean) / np.sqrt(var + 1e-5)
        np.testing.assert_allclose(np.asarray(y), expect, atol=1e-4)
        # perturbing rows OUTSIDE the subset must not change the stats
        x2 = x.at[8:].add(3.0)
        y2, mut2 = ours.apply(v, x2, mutable=["batch_stats"])
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, atol=0),
            mut["batch_stats"], mut2["batch_stats"],
        )
        np.testing.assert_allclose(np.asarray(y2[:8]), np.asarray(y[:8]), atol=1e-6)

    def test_stats_barrier_numerically_identical(self):
        """`stats_barrier` only breaks XLA fusion around the subset
        slice (the bn_compile_repro candidate workaround); outputs,
        running stats, and input gradients must match the plain slice
        path to float tolerance."""
        from moco_tpu.models.resnet import BatchNorm

        x = jax.random.normal(jax.random.PRNGKey(0), (16, 3, 3, 5))
        plain = BatchNorm(stats_rows=4, use_running_average=False)
        barred = BatchNorm(stats_rows=4, stats_barrier=True, use_running_average=False)
        v = plain.init(jax.random.PRNGKey(1), x)
        yp, mp = plain.apply(v, x, mutable=["batch_stats"])
        yb, mb = barred.apply(v, x, mutable=["batch_stats"])
        np.testing.assert_allclose(np.asarray(yp), np.asarray(yb), atol=1e-6)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, atol=1e-6),
            mp["batch_stats"], mb["batch_stats"],
        )
        gp = jax.grad(lambda x: plain.apply(v, x, mutable=["batch_stats"])[0].sum())(x)
        gb = jax.grad(lambda x: barred.apply(v, x, mutable=["batch_stats"])[0].sum())(x)
        np.testing.assert_allclose(np.asarray(gp), np.asarray(gb), atol=1e-5)

    def test_stats_barrier_without_rows_rejected(self):
        from moco_tpu.core import build_encoder
        from moco_tpu.utils.config import MocoConfig

        cfg = MocoConfig(
            arch="resnet18", shuffle="none", cifar_stem=True,
            bn_stats_barrier=True,
        )
        with pytest.raises(ValueError, match="bn_stats_barrier"):
            build_encoder(cfg)
        # the module-level gate catches direct construction too
        from moco_tpu.models.resnet import BatchNorm

        bn = BatchNorm(stats_barrier=True, use_running_average=False)
        x = jnp.zeros((4, 2, 2, 3))
        with pytest.raises(ValueError, match="stats_barrier"):
            bn.init(jax.random.PRNGKey(0), x)

    def test_running_stats_update_and_eval_mode(self):
        from moco_tpu.models.resnet import BatchNorm

        x = jax.random.normal(jax.random.PRNGKey(0), (16, 3, 3, 5)) * 2 + 1
        bn = BatchNorm(stats_rows=4, use_running_average=False, momentum=0.5)
        v = bn.init(jax.random.PRNGKey(1), x)
        _, mut = bn.apply(v, x, mutable=["batch_stats"])
        sub = np.asarray(x[:4], np.float64)
        mean = sub.mean(axis=(0, 1, 2))
        var = (sub**2).mean(axis=(0, 1, 2)) - mean**2
        np.testing.assert_allclose(
            np.asarray(mut["batch_stats"]["mean"]), 0.5 * mean, atol=1e-5
        )
        np.testing.assert_allclose(
            np.asarray(mut["batch_stats"]["var"]), 0.5 + 0.5 * var, atol=1e-5
        )
        ev = BatchNorm(stats_rows=4, use_running_average=True)
        y = ev.apply({"params": v["params"], "batch_stats": mut["batch_stats"]}, x)
        m = np.asarray(mut["batch_stats"]["mean"])
        s = np.sqrt(np.asarray(mut["batch_stats"]["var"]) + 1e-5)
        np.testing.assert_allclose(np.asarray(y), (np.asarray(x) - m) / s, atol=1e-4)

    def test_resnet_tree_paths_identical_across_modes(self):
        full = create_resnet("resnet18", cifar_stem=True)
        sub = create_resnet("resnet18", cifar_stem=True, bn_stats_rows=4)
        x = jnp.zeros((8, 32, 32, 3))
        vf = full.init(jax.random.PRNGKey(0), x, train=True)
        vs = sub.init(jax.random.PRNGKey(0), x, train=True)
        assert jax.tree_util.tree_structure(vf) == jax.tree_util.tree_structure(vs)
        # same init values too: a checkpoint from either mode loads in the other
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, atol=0), vf, vs
        )

    def test_subset_bn_rejected_with_unpermuted_multi_device_keys(self):
        # fixed first-r-rows statistics + shuffle='none' on a data axis
        # concentrates the BN leak Shuffle-BN prevents — must fail loudly
        import pytest

        from moco_tpu.core import build_encoder
        from moco_tpu.utils.config import MocoConfig

        cfg = MocoConfig(
            arch="resnet18", shuffle="none", cifar_stem=True, bn_stats_rows=2
        )
        with pytest.raises(ValueError, match="bn_stats_rows"):
            build_encoder(cfg, num_data=8)
        # the v3 step never shuffles — equally exposed, equally rejected
        cfg_v3 = MocoConfig(
            arch="resnet18", v3=True, num_negatives=0, shuffle="gather_perm",
            cifar_stem=True, bn_stats_rows=2,
        )
        with pytest.raises(ValueError, match="bn_stats_rows"):
            build_encoder(cfg_v3, num_data=8)
        # single-device stays available: pure perf lever, no cross-device
        # composition to leak
        build_encoder(cfg, num_data=1)

    def test_allow_leaky_bn_opts_into_the_cheat_config(self):
        # the BN-cheat positive control (scripts/ablate_shuffle.py arm
        # 'none' with virtual groups) needs the exact config the gates
        # reject; allow_leaky_bn=True is the explicit opt-in
        import pytest

        from moco_tpu.core import build_encoder
        from moco_tpu.utils.config import MocoConfig

        leaky = MocoConfig(
            arch="resnet18", shuffle="none", cifar_stem=True,
            bn_virtual_groups=4,
        )
        with pytest.raises(ValueError, match="bn_virtual_groups"):
            build_encoder(leaky, num_data=1)
        import dataclasses

        build_encoder(
            dataclasses.replace(leaky, allow_leaky_bn=True), num_data=1
        )
        subset = MocoConfig(
            arch="resnet18", shuffle="none", cifar_stem=True,
            bn_stats_rows=2, allow_leaky_bn=True,
        )
        build_encoder(subset, num_data=8)

    def test_train_step_runs_with_subset_bn(self):
        from moco_tpu.core import build_encoder, create_state, make_train_step, place_state
        from moco_tpu.parallel import create_mesh
        from moco_tpu.utils.config import (
            DataConfig, MocoConfig, OptimConfig, TrainConfig,
        )
        from moco_tpu.utils.schedules import build_optimizer

        cfg = TrainConfig(
            moco=MocoConfig(
                arch="resnet18", dim=16, num_negatives=64, mlp=True,
                shuffle="gather_perm", cifar_stem=True, compute_dtype="float32",
                bn_stats_rows=2,
            ),
            optim=OptimConfig(lr=0.03, epochs=1),
            data=DataConfig(dataset="synthetic", image_size=32, global_batch=16),
        )
        mesh = create_mesh()
        n = mesh.shape["data"]
        enc = build_encoder(cfg.moco, num_data=n)
        tx = build_optimizer(cfg.optim, steps_per_epoch=2)
        state = create_state(
            jax.random.PRNGKey(0), cfg, enc, tx, jnp.zeros((1, 32, 32, 3))
        )
        state = place_state(state, mesh)
        step = make_train_step(cfg, enc, tx, mesh)
        batch = {
            "im_q": jnp.zeros((16, 32, 32, 3), jnp.uint8),
            "im_k": jnp.zeros((16, 32, 32, 3), jnp.uint8),
        }
        rng = jax.device_put(
            jax.random.PRNGKey(2),
            jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec()),
        )
        state, metrics = step(state, batch, rng)
        assert np.isfinite(float(metrics["loss"]))


class TestVirtualGroupBatchNorm:
    """bn_virtual_groups: the reference's per-GPU BN inside one device's
    batch (grouped statistics + in-batch key permutation)."""

    def test_grouped_stats_match_manual(self):
        from moco_tpu.models.resnet import BatchNorm

        x = jax.random.normal(jax.random.PRNGKey(0), (8, 3, 3, 5)) * 2 + 1
        bn = BatchNorm(virtual_groups=4, use_running_average=False, momentum=0.5)
        v = bn.init(jax.random.PRNGKey(1), x)
        y, mut = bn.apply(v, x, mutable=["batch_stats"])
        xg = np.asarray(x, np.float64).reshape(4, 2, 3, 3, 5)
        mean = xg.mean(axis=(1, 2, 3))  # (4, 5)
        var = (xg**2).mean(axis=(1, 2, 3)) - mean**2
        expect = (xg - mean[:, None, None, None]) / np.sqrt(
            var[:, None, None, None] + 1e-5
        )
        np.testing.assert_allclose(
            np.asarray(y), expect.reshape(8, 3, 3, 5), atol=1e-4
        )
        # running stats = group average (matching the step's pmean)
        np.testing.assert_allclose(
            np.asarray(mut["batch_stats"]["mean"]), 0.5 * mean.mean(0), atol=1e-5
        )

    @pytest.mark.slow  # compiles the real 8-device shuffle-BN oracle program
    def test_virtual_groups_equal_multi_device_shuffle_bn(self):
        """The oracle: ONE device with bn_virtual_groups=G must produce
        the same training program as G devices with per-device BN and
        gather_perm Shuffle-BN — identical global permutation, identical
        group composition, identical statistics."""
        from moco_tpu.core import build_encoder, create_state, make_train_step, place_state
        from moco_tpu.parallel import create_mesh, shard_batch
        from moco_tpu.utils.config import (
            DataConfig, MocoConfig, OptimConfig, ParallelConfig, TrainConfig,
        )
        from moco_tpu.utils.schedules import build_optimizer

        batch, img, groups = 16, 32, 8

        def run(num_data, virtual):
            cfg = TrainConfig(
                moco=MocoConfig(
                    arch="resnet18", dim=16, num_negatives=64, mlp=True,
                    shuffle="gather_perm", cifar_stem=True,
                    compute_dtype="float32",
                    bn_virtual_groups=virtual,
                ),
                optim=OptimConfig(lr=0.03, epochs=1),
                data=DataConfig(dataset="synthetic", image_size=img, global_batch=batch),
                parallel=ParallelConfig(num_data=num_data),
            )
            mesh = create_mesh(num_data=num_data)
            enc = build_encoder(cfg.moco, num_data=num_data)
            tx = build_optimizer(cfg.optim, steps_per_epoch=2)
            state = create_state(
                jax.random.PRNGKey(0), cfg, enc, tx, jnp.zeros((1, img, img, 3))
            )
            state = place_state(state, mesh)
            step = make_train_step(cfg, enc, tx, mesh)
            ims = jax.random.uniform(
                jax.random.PRNGKey(7), (2, batch, img, img, 3)
            )
            b = shard_batch(
                mesh,
                {
                    "im_q": (ims[0] * 255).astype(jnp.uint8),
                    "im_k": (ims[1] * 255).astype(jnp.uint8),
                },
            )
            rng = jax.device_put(
                jax.random.PRNGKey(2),
                jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec()),
            )
            losses = []
            for _ in range(2):
                state, metrics = step(state, b, rng)
                losses.append(float(metrics["loss"]))
            return losses, state

        losses_multi, state_multi = run(num_data=groups, virtual=0)
        losses_virtual, state_virtual = run(num_data=1, virtual=groups)
        np.testing.assert_allclose(losses_multi, losses_virtual, rtol=2e-4)
        # the updated BN running stats agree too (pmean over devices ==
        # group-average inside the virtual batch)
        stats_m = jax.tree.map(np.asarray, jax.device_get(state_multi.batch_stats_k))
        stats_v = jax.tree.map(np.asarray, jax.device_get(state_virtual.batch_stats_k))
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, atol=1e-4), stats_m, stats_v
        )


class TestMomentumStatsBatchNorm:
    """Momentum-statistics BN (Momentum² Teacher, arXiv:2101.07525
    §3.2): normalize with the momentum-UPDATED running statistics and
    store them — the large-batch alternative to cross-replica BN."""

    def test_normalizes_and_stores_momentum_updated_stats(self):
        from moco_tpu.models.resnet import BatchNorm

        x = jax.random.normal(jax.random.PRNGKey(0), (16, 3, 3, 5)) * 2 + 1
        bn = BatchNorm(momentum_stats=True, use_running_average=False, momentum=0.5)
        v = bn.init(jax.random.PRNGKey(1), x)
        y, mut = bn.apply(v, x, mutable=["batch_stats"])
        xf = np.asarray(x, np.float64)
        bmean = xf.mean(axis=(0, 1, 2))
        bvar = (xf**2).mean(axis=(0, 1, 2)) - bmean**2
        # m_new = m * running + (1 - m) * batch, from the init stats (0, 1)
        m_mean = 0.5 * 0.0 + 0.5 * bmean
        m_var = 0.5 * 1.0 + 0.5 * bvar
        np.testing.assert_allclose(
            np.asarray(mut["batch_stats"]["mean"]), m_mean, atol=1e-5
        )
        np.testing.assert_allclose(
            np.asarray(mut["batch_stats"]["var"]), m_var, atol=1e-5
        )
        # ... and the NORMALIZATION used m_new, not the raw batch moments
        expect = (xf - m_mean) / np.sqrt(m_var + 1e-5)
        np.testing.assert_allclose(np.asarray(y), expect, atol=1e-4)

    def test_gradient_flows_through_batch_term(self):
        from moco_tpu.models.resnet import BatchNorm

        x = jax.random.normal(jax.random.PRNGKey(0), (8, 2, 2, 3))
        bn = BatchNorm(momentum_stats=True, use_running_average=False, momentum=0.9)
        v = bn.init(jax.random.PRNGKey(1), x)
        g = jax.grad(lambda x: bn.apply(v, x, mutable=["batch_stats"])[0].sum())(x)
        assert np.isfinite(np.asarray(g)).all()
        assert np.abs(np.asarray(g)).max() > 0  # the (1-m)*batch path is live

    def test_eval_mode_unchanged(self):
        """Eval normalizes with the stored running average exactly like
        plain BN — checkpoints interchange across the mode flag."""
        from moco_tpu.models.resnet import BatchNorm

        x = jax.random.normal(jax.random.PRNGKey(0), (8, 2, 2, 3))
        stats = {
            "mean": jnp.asarray([0.3, -0.1, 0.7]),
            "var": jnp.asarray([1.2, 0.5, 2.0]),
        }
        mom = BatchNorm(momentum_stats=True, use_running_average=True)
        plain = BatchNorm(use_running_average=True)
        v = plain.init(jax.random.PRNGKey(1), x)
        ym = mom.apply({"params": v["params"], "batch_stats": stats}, x)
        yp = plain.apply({"params": v["params"], "batch_stats": stats}, x)
        np.testing.assert_array_equal(np.asarray(ym), np.asarray(yp))

    def test_mutually_exclusive_with_other_stats_modes(self):
        from moco_tpu.core import build_encoder
        from moco_tpu.models.resnet import BatchNorm
        from moco_tpu.utils.config import MocoConfig

        x = jnp.zeros((4, 2, 2, 3))
        bn = BatchNorm(momentum_stats=True, stats_rows=2, use_running_average=False)
        with pytest.raises(ValueError, match="momentum_stats"):
            bn.init(jax.random.PRNGKey(0), x)
        bn = BatchNorm(momentum_stats=True, virtual_groups=2, use_running_average=False)
        with pytest.raises(ValueError, match="momentum_stats"):
            bn.init(jax.random.PRNGKey(0), x)
        # ViT has no BN: the encoder factory rejects the flag up front
        cfg = MocoConfig(
            arch="vit_tiny", v3=True, shuffle="none", vit_patch_size=4,
            bn_momentum_stats=True,
        )
        with pytest.raises(ValueError, match="bn_momentum_stats"):
            build_encoder(cfg)


class TestLayerGroupedApply:
    """The layer-granular ZeRO-3 seam (ISSUE 20): applying the backbone
    group by group — the param tree restricted to each group's own
    children — must reproduce the whole-model apply BIT-identically,
    and the declared group->param-child map must tile the tree."""

    def _grouped_forward(self, model, variables, x, train=True):
        names = model.group_param_names()
        stats = variables.get("batch_stats", {})
        out = x
        for g in model.group_names:
            params_g = {k: variables["params"][k] for k in names[g]}
            out, mut = model.apply(
                {"params": params_g, "batch_stats": stats},
                out, train=train, group=g, mutable=["batch_stats"],
            )
            stats = {**stats, **mut.get("batch_stats", {})}
        return out, stats

    @pytest.mark.parametrize("arch", ["resnet18", "resnet50"])
    def test_grouped_matches_whole_apply_bitwise(self, arch):
        model = create_resnet(arch, cifar_stem=True)
        x = jax.random.normal(jax.random.PRNGKey(0), (4, 16, 16, 3))
        v = model.init(jax.random.PRNGKey(1), x, train=False)
        whole, mut = model.apply(
            v, x, train=True, mutable=["batch_stats"]
        )
        grouped, stats = self._grouped_forward(model, v, x)
        np.testing.assert_array_equal(np.asarray(whole), np.asarray(grouped))
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
            mut["batch_stats"], stats,
        )

    def test_group_param_names_tile_the_tree(self):
        model = create_resnet("resnet18", cifar_stem=True)
        v = model.init(
            jax.random.PRNGKey(0), jnp.zeros((1, 16, 16, 3)), train=False
        )
        names = model.group_param_names()
        claimed = [c for g in model.group_names for c in names[g]]
        assert sorted(claimed) == sorted(v["params"].keys())
        assert len(claimed) == len(set(claimed))

    def test_unknown_group_rejected(self):
        model = create_resnet("resnet18", cifar_stem=True)
        v = model.init(
            jax.random.PRNGKey(0), jnp.zeros((1, 16, 16, 3)), train=False
        )
        with pytest.raises(ValueError, match="unknown layer group"):
            model.apply(v, jnp.zeros((1, 16, 16, 3)), train=True, group="nope")
        with pytest.raises(ValueError, match="out of range"):
            model.apply(v, jnp.zeros((1, 16, 16, 3)), train=True, group="block99")
