"""Model-family tests: shapes, parameter counts vs torchvision, BN modes."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from moco_tpu.models import create_resnet, ProjectionHead, LinearClassifier


def n_params(tree):
    return sum(np.prod(x.shape) for x in jax.tree.leaves(tree))


# torchvision backbone param counts (fc excluded), ground truth from
# torchvision.models.resnet*(num_classes=...) minus fc params.
TORCHVISION_BACKBONE_PARAMS = {
    "resnet18": 11_176_512,
    "resnet50": 23_508_032,
}


@pytest.mark.parametrize("arch", ["resnet18", "resnet50"])
def test_param_count_matches_torchvision(arch):
    model = create_resnet(arch)
    variables = model.init(jax.random.key(0), jnp.zeros((1, 32, 32, 3)), train=False)
    got = n_params(variables["params"])
    assert got == TORCHVISION_BACKBONE_PARAMS[arch], (arch, got)


def test_forward_shapes_and_features():
    model = create_resnet("resnet18", cifar_stem=True)
    variables = model.init(jax.random.key(0), jnp.zeros((2, 32, 32, 3)), train=False)
    out = model.apply(variables, jnp.ones((2, 32, 32, 3)), train=False)
    assert out.shape == (2, 512)
    assert model.num_features == 512
    assert create_resnet("resnet50").num_features == 2048


def test_train_mode_updates_batch_stats():
    model = create_resnet("resnet18", cifar_stem=True)
    x = jax.random.normal(jax.random.key(1), (4, 16, 16, 3))
    variables = model.init(jax.random.key(0), x, train=False)
    _, mutated = model.apply(variables, x, train=True, mutable=["batch_stats"])
    before = jax.tree.leaves(variables["batch_stats"])
    after = jax.tree.leaves(mutated["batch_stats"])
    assert any(not np.allclose(b, a) for b, a in zip(before, after))


def test_eval_mode_is_deterministic_wrt_batch():
    """Eval BN must use running stats: per-sample output independent of
    batch composition."""
    model = create_resnet("resnet18", cifar_stem=True)
    x = jax.random.normal(jax.random.key(1), (4, 16, 16, 3))
    variables = model.init(jax.random.key(0), x, train=False)
    full = model.apply(variables, x, train=False)
    half = model.apply(variables, x[:2], train=False)
    np.testing.assert_allclose(full[:2], half, rtol=1e-3, atol=1e-5)


def test_bf16_compute_fp32_out():
    model = create_resnet("resnet18", cifar_stem=True, dtype=jnp.bfloat16)
    x = jnp.ones((2, 16, 16, 3))
    variables = model.init(jax.random.key(0), x, train=False)
    out = model.apply(variables, x, train=False)
    assert out.dtype == jnp.float32
    # params stay fp32 (param_dtype default)
    assert all(p.dtype == jnp.float32 for p in jax.tree.leaves(variables["params"]))


def test_projection_heads():
    feats = jnp.ones((2, 512))
    for mlp, expect_params in [(False, 512 * 128 + 128), (True, 512 * 512 + 512 + 512 * 128 + 128)]:
        head = ProjectionHead(dim=128, mlp=mlp)
        v = head.init(jax.random.key(0), feats)
        assert head.apply(v, feats).shape == (2, 128)
        assert n_params(v["params"]) == expect_params


def test_linear_classifier_init():
    head = LinearClassifier(num_classes=10)
    v = head.init(jax.random.key(0), jnp.ones((2, 512)))
    k = v["params"]["Dense_0"]["kernel"]
    assert np.abs(k).std() < 0.02 and not np.allclose(k, 0)
    assert np.allclose(v["params"]["Dense_0"]["bias"], 0)
