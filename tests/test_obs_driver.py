"""End-to-end telemetry: the ≤5-step CPU driver smoke must produce the
full observability surface (ISSUE-3 acceptance bullet).

The assertions live in `scripts/obs_smoke.py` (CI's tier-1 job runs the
same script and uploads its workdir as artifacts); here they run under
pytest against a fresh driver run. Slow-marked like the other
full-driver e2e tests — the obs-smoke CI step covers every PR."""

import json
import os

import pytest

from conftest import load_script


@pytest.fixture(scope="module")
def smoke(tmp_path_factory):
    mod = load_script("obs_smoke.py")
    workdir = str(tmp_path_factory.mktemp("obs_smoke"))
    out = mod.run_smoke(workdir)
    return mod, workdir, out


@pytest.mark.slow
def test_driver_smoke_produces_obs_surface(smoke):
    """Chrome trace with nested epoch/step/data_wait spans; JSONL lines
    with t_data/t_step, hbm gauges (null on CPU), queue_age_mean,
    ema_drift, logit pos/neg means; schema-clean; CSV sink populated."""
    mod, workdir, _ = smoke
    mod.assert_obs_surface(workdir)


@pytest.mark.slow
def test_obs_report_renders_driver_run(smoke):
    """`scripts/obs_report.py` renders the real run without error and
    covers every section (the satellite's anti-rot check)."""
    _, workdir, _ = smoke
    report_mod = load_script("obs_report.py")
    report = report_mod.render_report(
        os.path.join(workdir, "metrics.jsonl"), os.path.join(workdir, "trace.json")
    )
    for section in (
        "Step-time breakdown", "Device memory", "Training health",
        "Fault ledger", "Trace summary",
    ):
        assert section in report
    assert "ema_drift" in report and "queue_age_mean" in report


@pytest.mark.slow
def test_driver_trace_json_loads_and_nests(smoke):
    """The golden acceptance check, independent of the smoke script's
    own assertions: the exported file is plain JSON, and the epoch span
    contains its step spans by timestamp on the driver thread."""
    _, workdir, _ = smoke
    with open(os.path.join(workdir, "trace.json")) as f:
        trace = json.load(f)
    xs = [e for e in trace["traceEvents"] if e.get("ph") == "X"]
    epoch = next(e for e in xs if e["name"] == "epoch")
    steps = [e for e in xs if e["name"] == "step" and e["tid"] == epoch["tid"]]
    assert len(steps) == 3
    for s in steps:
        assert epoch["ts"] <= s["ts"]
        assert s["ts"] + s["dur"] <= epoch["ts"] + epoch["dur"] + 1
