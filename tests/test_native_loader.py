"""Native C++ loader: build, decode parity vs PIL, batch semantics.

`native/loader.cc` is the rebuild's first-party native component
(the reference has none in-tree, SURVEY.md §2.2 — its decode ran inside
torch DataLoader worker processes; ours is a C++ thread pool)."""

import os

import numpy as np
import pytest

PIL = pytest.importorskip("PIL")
from PIL import Image

from moco_tpu.data.native_loader import (
    NativeBatchLoader,
    NativeImageFolderDataset,
    native_available,
)

pytestmark = pytest.mark.skipif(not native_available(), reason="native loader not built")


@pytest.fixture(scope="module")
def image_dir(tmp_path_factory):
    """A tiny ImageFolder tree with JPEG + PNG of varied sizes."""
    root = tmp_path_factory.mktemp("imgs")
    rng = np.random.default_rng(0)
    sizes = [(64, 48), (48, 64), (100, 100), (37, 53)]
    paths = []
    for cls in ("a", "b"):
        (root / cls).mkdir()
        for i, (w, h) in enumerate(sizes):
            arr = rng.integers(0, 256, (h, w, 3), dtype=np.uint8)
            ext = "jpg" if i % 2 == 0 else "png"
            p = root / cls / f"img_{i}.{ext}"
            Image.fromarray(arr).save(p, quality=95)
            paths.append(str(p))
    return str(root), paths


def test_batch_shape_and_determinism(image_dir):
    root, paths = image_dir
    loader = NativeBatchLoader(paths, canvas=32, threads=4)
    idx = np.arange(len(paths))
    out1 = loader.load_batch(idx)
    out2 = loader.load_batch(idx)
    assert out1.shape == (len(paths), 32, 32, 3)
    assert out1.dtype == np.uint8
    np.testing.assert_array_equal(out1, out2)
    # images are non-degenerate (decode actually happened)
    assert out1.std() > 10


def test_decode_parity_with_pil(image_dir):
    """Native decode+resize+crop ≈ the Python ImageFolderDataset path.
    JPEG decoders and resamplers differ slightly; mean abs diff must be
    small (a few gray levels), which is invisible after augmentation."""
    from moco_tpu.data.datasets import ImageFolderDataset

    root, _ = image_dir
    py = ImageFolderDataset(root, decode_size=32)
    nat = NativeImageFolderDataset(root, decode_size=32)
    assert len(py) == len(nat)
    for i in range(len(py)):
        a, la = py.load(i)
        b, lb = nat.load(i)
        assert la == lb
        assert a.shape == b.shape == (32, 32, 3)
        diff = np.abs(a.astype(np.float32) - b.astype(np.float32)).mean()
        assert diff < 6.0, f"index {i}: mean abs diff {diff}"


def test_out_of_range_index_zero_fills(image_dir):
    root, paths = image_dir
    loader = NativeBatchLoader(paths, canvas=16, threads=2)
    with pytest.warns(UserWarning, match="failed to decode"):
        out = loader.load_batch(np.asarray([0, 10_000]))
    assert out[1].max() == 0  # failed slot zero-filled
    assert out[0].std() > 0


def test_unsupported_format_falls_back_to_pil(tmp_path):
    """Formats the C++ decoders lack (bmp) retry through PIL per slot —
    never silently-black frames."""
    root = tmp_path / "tree"
    (root / "a").mkdir(parents=True)
    rng = np.random.default_rng(3)
    arr = rng.integers(0, 256, (40, 56, 3), dtype=np.uint8)
    Image.fromarray(arr).save(root / "a" / "img.bmp")
    Image.fromarray(arr).save(root / "a" / "img.jpg", quality=95)
    nat = NativeImageFolderDataset(str(root), decode_size=32)
    from moco_tpu.data.datasets import ImageFolderDataset

    py = ImageFolderDataset(str(root), decode_size=32)
    for i in range(len(nat)):
        b, _ = nat.load(i)
        a, _ = py.load(i)
        assert b.std() > 5, "fallback produced a blank frame"
        diff = np.abs(a.astype(np.float32) - b.astype(np.float32)).mean()
        assert diff < 6.0


def test_decode_size_override_rejected(image_dir):
    root, _ = image_dir
    nat = NativeImageFolderDataset(root, decode_size=32)
    with pytest.raises(ValueError, match="fixed canvas"):
        nat.load(0, decode_size=64)


def test_labels_match_folder_classes(image_dir):
    root, _ = image_dir
    nat = NativeImageFolderDataset(root, decode_size=16)
    imgs, labels = nat.load_batch(np.arange(len(nat)))
    assert set(labels.tolist()) == {0, 1}
    assert imgs.shape[0] == len(nat)


def test_pipeline_uses_native_batch(image_dir):
    """TwoCropPipeline._host_batch must take the load_batch fast path."""
    import jax

    from moco_tpu.data.pipeline import TwoCropPipeline
    from moco_tpu.parallel import create_mesh
    from moco_tpu.utils.config import DataConfig

    root, _ = image_dir
    nat = NativeImageFolderDataset(root, decode_size=32)
    mesh = create_mesh(num_data=1, num_model=1, devices=jax.devices()[:1])
    cfg = DataConfig(dataset="imagefolder", data_dir=root, image_size=32, global_batch=4)
    pipe = TwoCropPipeline(cfg, mesh, dataset=nat)
    batch = next(iter(pipe.epoch(0)))
    assert batch["im_q"].shape == (4, 32, 32, 3)


def test_get_dims_matches_originals(image_dir):
    root, paths = image_dir
    loader = NativeBatchLoader(paths, canvas=32, threads=2)
    dims = loader.get_dims(np.arange(len(paths)))
    for i, p in enumerate(paths):
        with Image.open(p) as im:
            w, h = im.size
        assert tuple(dims[i]) == (h, w)
    # cached second call identical
    np.testing.assert_array_equal(dims, loader.get_dims(np.arange(len(paths))))


def test_load_crops_parity_with_pil(image_dir):
    """Native region-resize == PIL crop+resize (both BILINEAR antialias),
    for boxes sampled against ORIGINAL geometry — the exact-crop path of
    VERDICT r1 weak-item 6."""
    root, paths = image_dir
    loader = NativeBatchLoader(paths, canvas=32, threads=2)
    idx = np.arange(len(paths))
    dims = loader.get_dims(idx)
    from moco_tpu.data.datasets import sample_rrc_boxes

    rng = np.random.default_rng(3)
    boxes = np.stack(
        [sample_rrc_boxes(rng, dims), sample_rrc_boxes(rng, dims)], axis=1
    )
    out = loader.load_crops(idx, boxes, out_size=24)
    assert out.shape == (len(paths), 2, 24, 24, 3)
    for i, p in enumerate(paths):
        with Image.open(p) as im:
            im = im.convert("RGB")
            for c in range(2):
                y0, x0, ch, cw = boxes[i, c]
                want = np.asarray(
                    im.crop((x0, y0, x0 + cw, y0 + ch)).resize((24, 24), Image.BILINEAR),
                    np.float32,
                )
                diff = np.abs(out[i, c].astype(np.float32) - want).mean()
                assert diff < 6.0, f"img {i} crop {c}: mean abs diff {diff}"


def test_imagefolder_crop_protocol_parity(image_dir):
    """PIL ImageFolderDataset and NativeImageFolderDataset expose the same
    host-crop protocol with matching outputs."""
    from moco_tpu.data.datasets import ImageFolderDataset, sample_rrc_boxes

    root, _ = image_dir
    py = ImageFolderDataset(root, decode_size=32)
    nat = NativeImageFolderDataset(root, decode_size=32)
    idx = np.arange(len(py))
    np.testing.assert_array_equal(py.dims(idx), nat.dims(idx))
    boxes = sample_rrc_boxes(np.random.default_rng(0), py.dims(idx))[:, None]
    a, la = py.load_crop_batch(idx, boxes, 16)
    b, lb = nat.load_crop_batch(idx, boxes, 16)
    np.testing.assert_array_equal(la, lb)
    assert a.shape == b.shape == (len(py), 1, 16, 16, 3)
    diff = np.abs(a.astype(np.float32) - b.astype(np.float32)).mean()
    assert diff < 6.0


def test_decode_failures_counter(tmp_path):
    """Doubly-failed slots (native + PIL) zero-fill AND count — the
    `decode_failures` surface the pipeline reports (fault-tolerance
    layer); recoverable PIL-fallback slots do not count."""
    root = tmp_path / "imgs"
    (root / "a").mkdir(parents=True)
    rng = np.random.default_rng(0)
    arr = rng.integers(0, 256, (40, 40, 3), dtype=np.uint8)
    Image.fromarray(arr).save(root / "a" / "good.jpg", quality=95)
    (root / "a" / "corrupt.jpg").write_bytes(b"\xff\xd8\xff definitely not jpeg")

    ds = NativeImageFolderDataset(str(root), decode_size=32, threads=2)
    assert ds.decode_failures == 0
    with pytest.warns(UserWarning, match="failed to decode"):
        imgs, _ = ds.load_batch(np.arange(len(ds)))
    assert ds.decode_failures == 1
    # the good slot decoded, the corrupt one zero-filled
    sums = imgs.reshape(len(ds), -1).sum(axis=1)
    assert (sums == 0).sum() == 1 and (sums > 0).sum() == 1
