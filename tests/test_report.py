"""Marker-delimited report section helpers (moco_tpu/utils/report.py) —
the evidence scripts all write through these; a splice bug would corrupt
REPORT.md/PROFILE.md silently."""


from moco_tpu.utils.report import extract_marker_blocks, replace_marker_block


def test_insert_into_missing_file(tmp_path):
    p = str(tmp_path / "r.md")
    replace_marker_block(p, "abl", "## T\ndata")
    text = open(p).read()
    assert text == "<!-- abl:begin -->\n## T\ndata\n<!-- abl:end -->\n"


def test_append_preserves_existing_body_and_replace_is_idempotent(tmp_path):
    p = str(tmp_path / "r.md")
    with open(p, "w") as f:
        f.write("# Head\n\nbody\n")
    replace_marker_block(p, "abl", "v1")
    replace_marker_block(p, "abl", "v2")
    text = open(p).read()
    assert text.startswith("# Head\n\nbody\n")
    assert text.count("<!-- abl:begin -->") == 1
    assert "v2" in text and "v1" not in text


def test_two_markers_coexist_and_extract_roundtrips(tmp_path):
    p = str(tmp_path / "r.md")
    with open(p, "w") as f:
        f.write("intro\n")
    replace_marker_block(p, "abl", "table-a")
    replace_marker_block(p, "v3-signal", "table-b")
    replace_marker_block(p, "abl", "table-a2")  # replace first, keep second
    text = open(p).read()
    blocks = extract_marker_blocks(text)
    assert len(blocks) == 2
    assert "table-a2" in blocks[0] and "table-b" in blocks[1]
    # replacing a block never duplicates or reorders the others
    assert text.index("abl:begin") < text.index("v3-signal:begin")


def test_extract_ignores_mismatched_markers():
    text = "<!-- a:begin -->x<!-- b:end -->\n<!-- c:begin -->y<!-- c:end -->"
    blocks = extract_marker_blocks(text)
    assert len(blocks) == 1 and "y" in blocks[0]


def test_orphan_end_before_begin_does_not_corrupt(tmp_path):
    # an end marker BEFORE the begin marker (hand edit / truncated write)
    # must not drive the splice backwards through surrounding text
    p = str(tmp_path / "r.md")
    with open(p, "w") as f:
        f.write("intro\n<!-- abl:end -->\nmiddle\n<!-- abl:begin -->\nold\n"
                "<!-- abl:end -->\ntail\n")
    replace_marker_block(p, "abl", "new")
    text = open(p).read()
    assert "intro" in text and "middle" in text and "tail" in text
    assert "new" in text and "old" not in text


def test_orphan_begin_without_end_raises(tmp_path):
    import pytest

    p = str(tmp_path / "r.md")
    with open(p, "w") as f:
        f.write("head\n<!-- abl:begin -->\ntruncated")
    with pytest.raises(ValueError, match="unbalanced"):
        replace_marker_block(p, "abl", "new")
    assert "truncated" in open(p).read()  # file untouched on error


def test_seed_variance_pools_majority_budget_and_names_strays():
    """A stray arm produced at different flags must not block table
    regeneration: it is dropped from pooling and named in the section;
    single-seed arms render without a fake variance estimate."""
    from tests.conftest import load_script

    svr = load_script("seed_variance_report.py")

    def arm(name, seed, epochs=10, knn=50.0):
        return {
            "arm": name, "seed": seed, "epochs": epochs, "examples": 1024,
            "global_batch": 64, "queue": 2048, "num_devices": 8,
            "dataset": "synthetic_learnable", "final_knn_top1": knn,
            "contrast_acc_tail_mean": 10.0,
        }

    results = {
        "gather_perm": [arm("gather_perm", 0, knn=53.0),
                        arm("gather_perm", 1, knn=54.0)],
        "a2a": [arm("a2a", 0, knn=51.0),
                # stray: different budget — must be excluded by name
                arm("a2a", 1, epochs=12, knn=99.0)],
        "syncbn": [],
        # single seed: no variance estimate may be claimed
        "eman": [arm("eman", 0, knn=35.0)],
    }
    section = svr.render_section(results)
    assert "Excluded from pooling" in section and "a2a/s1" in section
    assert "99.0" not in section  # the stray's kNN never enters the table
    assert "n=1 seed, no variance estimate" in section
    # header reports the true pooled seed union
    assert "[0, 1]" in section
