"""Elastic training (parallel/elastic.py + the driver's rescale loop):
heartbeat-staleness detection, the rescale-consensus barrier, the
feasible-width policy, the auto-scale hyperparameter derivation
(m^kappa / linear LR), the kill@host chaos fault, the rescale event
schema, the graceful-preemption (SIGTERM) emergency-checkpoint path,
and the retry-wrapped serve_ingest POSTs."""

import dataclasses
import json
import os
import threading
import time

import pytest

from moco_tpu.parallel.elastic import (
    ElasticCoordinator,
    RescalePlan,
    feasible_width,
    plan_rescale,
    rescale_path,
    surviving_devices,
)
from moco_tpu.utils.config import (
    DataConfig,
    MocoConfig,
    TrainConfig,
    apply_auto_scale,
    parse_auto_scale,
    config_from_dict,
    config_to_dict,
)

from conftest import load_script


def _beat(workdir, process, t):
    path = os.path.join(workdir, f"heartbeat.p{process}.json")
    with open(path, "w") as f:
        json.dump({"process": process, "time": t, "step": 1, "epoch": 0}, f)


# -- feasible-width policy ------------------------------------------------


def test_feasible_width_keeps_queue_divisibility():
    # per-device batch 8, K=128: 7/6/5 all break K % global == 0 -> 4
    assert feasible_width(7, 8, 128) == 4
    # a divisible width survives as-is
    assert feasible_width(6, 8, 96) == 6
    # queue-free (v3): any surviving width works
    assert feasible_width(7, 8, 0) == 7


def test_feasible_width_errors():
    with pytest.raises(ValueError, match="no surviving hosts"):
        feasible_width(0, 8, 128)
    with pytest.raises(ValueError, match="divisible"):
        feasible_width(3, 7, 128)  # 128 % 7/14/21 != 0


# -- auto-scale derivation ------------------------------------------------


def test_parse_auto_scale():
    assert parse_auto_scale("") is None
    assert parse_auto_scale("ref_batch=256") == 256
    with pytest.raises(ValueError):
        parse_auto_scale("ref_batch=0")
    with pytest.raises(ValueError):
        parse_auto_scale("batch=256")


def test_apply_auto_scale_identity_and_kappa():
    base = TrainConfig(
        moco=MocoConfig(momentum=0.99),
        data=DataConfig(global_batch=128),
    )
    same, info = apply_auto_scale(base)
    assert same is base and info is None

    cfg = dataclasses.replace(base, auto_scale="ref_batch=256")
    derived, info = apply_auto_scale(cfg)
    assert info["kappa"] == 0.5
    assert derived.optim.lr == pytest.approx(cfg.optim.lr * 0.5)
    assert derived.moco.momentum == pytest.approx(0.99**0.5)
    # always derives from the passed (reference) values: re-applying to
    # the reference gives the same result, not a compounded one
    derived2, _ = apply_auto_scale(cfg)
    assert derived2.optim.lr == derived.optim.lr


def test_config_roundtrips_elastic_fields():
    cfg = TrainConfig(elastic=True, heartbeat_timeout=7.5, auto_scale="ref_batch=64")
    rt = config_from_dict(config_to_dict(cfg))
    assert rt.elastic and rt.heartbeat_timeout == 7.5
    assert rt.auto_scale == "ref_batch=64"


# -- rescale planning -----------------------------------------------------


def test_plan_rescale_derives_mesh_batch_and_hyperparams():
    cfg = TrainConfig(
        moco=MocoConfig(num_negatives=128, momentum=0.99),
        data=DataConfig(global_batch=64),
        auto_scale="ref_batch=64",
    )
    plan, new_ref, info = plan_rescale(cfg, 8, 1, [2], step=3)
    assert plan.old_num_data == 8 and plan.new_num_data == 4
    assert plan.old_global_batch == 64 and plan.new_global_batch == 32
    assert plan.dead_hosts == (2,)
    assert new_ref.parallel.num_data == 4
    assert new_ref.data.global_batch == 32
    # the reference hyperparameters stay the anchor in the new ref config
    assert new_ref.optim.lr == cfg.optim.lr
    assert info["kappa"] == 0.5
    assert info["momentum"] == pytest.approx(0.99**0.5)
    assert info["lr"] == pytest.approx(cfg.optim.lr * 0.5)


def test_plan_rescale_rejects_model_parallel():
    cfg = TrainConfig(data=DataConfig(global_batch=64))
    with pytest.raises(ValueError, match="num_model=1"):
        plan_rescale(cfg, 8, 2, [2], step=3)


def test_surviving_devices_excludes_dead_host_indices():
    import jax

    devs = surviving_devices([2, 5])
    assert len(devs) == len(jax.devices()) - 2
    assert jax.devices()[2] not in devs and jax.devices()[5] not in devs


# -- heartbeat-staleness detection ---------------------------------------


def test_stale_hosts_flags_only_new_dead(tmp_path):
    now = time.time()
    _beat(tmp_path, 0, now)  # self
    _beat(tmp_path, 1, now - 1.0)  # fresh
    _beat(tmp_path, 2, 0.0)  # dead
    _beat(tmp_path, 3, now - 100.0)  # dead
    _beat(tmp_path, 4, 0.0)  # dead but already rescaled away
    coord = ElasticCoordinator(
        str(tmp_path), process_index=0, num_processes=5, timeout=10.0, known_dead=[4]
    )
    assert coord.stale_hosts(now=now) == [2, 3]
    # a revived host drops off the stale list
    _beat(tmp_path, 2, now)
    assert coord.stale_hosts(now=now) == [3]


def test_stale_hosts_ignores_hosts_that_never_beat(tmp_path):
    _beat(tmp_path, 0, time.time())
    coord = ElasticCoordinator(str(tmp_path), 0, num_processes=8, timeout=5.0)
    assert coord.stale_hosts() == []


# -- rescale-consensus barrier -------------------------------------------


def _plan(dead=(2,), new_n=4, new_b=32, step=3):
    return RescalePlan(
        step=step, dead_hosts=tuple(dead), old_num_data=8, new_num_data=new_n,
        old_global_batch=64, new_global_batch=new_b,
    )


def test_consensus_barrier_agrees_across_survivors(tmp_path):
    """Two survivors of a 3-host fleet (host 2 dead) publish matching
    plans from separate threads; both clear the barrier."""
    coords = [
        ElasticCoordinator(str(tmp_path), p, num_processes=3, barrier_timeout=5.0)
        for p in (0, 1)
    ]
    results, errors = {}, []

    def run(i):
        try:
            results[i] = coords[i].agree(_plan(step=3 + i))  # step may differ
        except Exception as e:  # pragma: no cover - surfaced by assert
            errors.append(e)

    threads = [threading.Thread(target=run, args=(i,)) for i in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=10)
    assert not errors and set(results) == {0, 1}
    for p in (0, 1):
        assert os.path.exists(rescale_path(str(tmp_path), p))


def test_consensus_barrier_times_out_without_peer(tmp_path):
    coord = ElasticCoordinator(
        str(tmp_path), 0, num_processes=2, barrier_timeout=0.3, poll_interval=0.02
    )
    with pytest.raises(RuntimeError, match="timed out"):
        coord.agree(_plan())


def test_consensus_barrier_rejects_conflicting_plan(tmp_path):
    # peer 1 freshly proposes a DIFFERENT world -> split brain, abort
    with open(rescale_path(str(tmp_path), 1), "w") as f:
        json.dump(
            {"process": 1, "time": time.time(), "dead_hosts": [3],
             "new_num_data": 2, "new_global_batch": 16},
            f,
        )
    coord = ElasticCoordinator(
        str(tmp_path), 0, num_processes=2, barrier_timeout=1.0, poll_interval=0.02
    )
    with pytest.raises(RuntimeError, match="conflict"):
        coord.agree(_plan())


def test_consensus_barrier_ignores_stale_previous_round(tmp_path):
    """A leftover file from a PREVIOUS rescale (old timestamp, smaller
    dead set) must not read as a conflict — the barrier waits for the
    peer to overwrite it (and times out here, since none does)."""
    with open(rescale_path(str(tmp_path), 1), "w") as f:
        json.dump(
            {"process": 1, "time": time.time() - 3600, "dead_hosts": [],
             "new_num_data": 8, "new_global_batch": 64},
            f,
        )
    coord = ElasticCoordinator(
        str(tmp_path), 0, num_processes=2, barrier_timeout=0.3, poll_interval=0.02
    )
    with pytest.raises(RuntimeError, match="timed out"):
        coord.agree(_plan())


# -- alerts: configurable heartbeat threshold ----------------------------


def test_default_alert_spec_takes_heartbeat_timeout():
    from moco_tpu.obs.alerts import parse_rules

    hb = [r for r in parse_rules("default", heartbeat_timeout=9.0) if r.kind == "heartbeat"]
    assert hb and hb[0].timeout == 9.0
    # explicit heartbeat@ rules keep their own timeout
    spec = "default,heartbeat@name=custom_hb:timeout=33"
    rules = {r.name: r for r in parse_rules(spec, heartbeat_timeout=9.0)}
    assert rules["heartbeat_loss"].timeout == 9.0
    assert rules["custom_hb"].timeout == 33.0


# -- schema: rescale / preempt event lines -------------------------------


def test_rescale_event_line_schema():
    from moco_tpu.obs.schema import validate_line

    line = {
        "step": 3, "time": 1.0, "epoch": 1, "event": "rescale",
        "rescale/dead_hosts": [2], "rescale/old_num_data": 8,
        "rescale/new_num_data": 4, "rescale/old_global_batch": 64,
        "rescale/new_global_batch": 32, "rescale/kappa": 0.5,
        "rescale/lr": 0.015, "rescale/momentum": 0.99498,
    }
    assert validate_line(line) == []
    assert validate_line({**line, "rescale/new_num_data": "four"})
    assert validate_line({**line, "rescale/dead_hosts": "2"})
    assert validate_line({"step": 1, "time": 1.0, "epoch": 0, "event": "preempt"}) == []


# -- serve_ingest: retry-wrapped POSTs -----------------------------------


class _FakeResponse:
    def __init__(self, payload):
        self._payload = payload

    def read(self):
        return self._payload

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


def test_serve_ingest_posts_retry_through_backoff(monkeypatch):
    """A replica restart mid-tail (one connection-refused POST) degrades
    to a logged retry at site ingest.post — the block is re-POSTed, not
    dropped."""
    import urllib.error

    import numpy as np

    from moco_tpu.utils import retry

    ingest = load_script("serve_ingest.py")
    calls = {"n": 0}

    def flaky_urlopen(req, timeout=0):
        calls["n"] += 1
        if calls["n"] == 1:
            raise urllib.error.URLError("connection refused")
        return _FakeResponse(json.dumps({"index_rows": 7}).encode())

    monkeypatch.setattr(ingest, "_urlopen", flaky_urlopen)
    monkeypatch.setattr(retry, "_retries", retry._retries.__class__())
    rows = np.zeros((3, 4), np.float32)
    got = ingest.post_rows("http://127.0.0.1:9", rows, block=8)
    assert got == 7 and calls["n"] == 2
    assert retry.snapshot().get("ingest.post") == 1


def test_serve_ingest_propagates_persistent_failure(monkeypatch):
    import urllib.error

    import numpy as np

    ingest = load_script("serve_ingest.py")

    def down(req, timeout=0):
        raise urllib.error.URLError("still down")

    monkeypatch.setattr(ingest, "_urlopen", down)
    monkeypatch.setenv("MOCO_IO_RETRY_BASE", "0.001")
    monkeypatch.setenv("MOCO_IO_RETRY_MAX", "0.002")
    with pytest.raises(urllib.error.URLError):
        ingest.post_rows("http://127.0.0.1:9", np.zeros((1, 4), np.float32))


# -- driver end-to-end (slow: full chaos run, same path CI's smoke runs) --


@pytest.mark.slow
def test_elastic_driver_rescales_and_finishes(tmp_path):
    """The acceptance chaos run, in-process: kill@host=2 on a fake-8
    ZeRO-2/3 mesh -> heartbeat staleness -> consensus -> emergency
    checkpoint -> 8->4 reshard -> m^kappa / linear-LR rescale -> resume
    to completion, loss within tolerance of the uninterrupted control."""
    smoke = load_script("elastic_smoke.py")
    control = smoke.run_control(str(tmp_path / "control"))
    chaos = smoke.run_chaos(str(tmp_path / "chaos"))
    summary = smoke.assert_surface(str(tmp_path / "chaos"), chaos, control)
    assert summary["rescale_event"]["rescale/new_num_data"] == 4


@pytest.mark.slow
def test_sigterm_to_driver_subprocess_takes_emergency_path(tmp_path):
    """Graceful preemption the way preemptible VMs announce it: SIGTERM
    to a real driver subprocess -> `event: "preempt"` metrics line, a
    durable emergency checkpoint tagged with the reason, exit 0."""
    import signal
    import subprocess
    import sys

    workdir = str(tmp_path / "preempt")
    script = os.path.join(os.path.dirname(__file__), "..", "scripts", "chaos_smoke.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, script, "--worker", "--workdir", workdir, "--epochs", "50"],
        env=env,
    )
    try:
        metrics = os.path.join(workdir, "metrics.jsonl")
        deadline = time.time() + 300
        while time.time() < deadline:
            if os.path.exists(metrics) and os.path.getsize(metrics) > 0:
                break
            time.sleep(0.5)
        else:  # pragma: no cover
            pytest.fail("driver subprocess produced no metrics in time")
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=300)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert rc == 0  # graceful: saved, then returned
    lines = [json.loads(l) for l in open(metrics) if l.strip()]
    assert any(l.get("event") == "preempt" for l in lines)

    from moco_tpu.utils.checkpoint import CheckpointManager

    mgr = CheckpointManager(workdir)
    step = mgr.latest_step()
    assert step is not None
    extra = mgr.read_extra(step)
    mgr.close()
    assert extra.get("reason") == "preempt" and extra.get("emergency") is True
    assert extra["epoch"] < 49  # exited long before the configured run
