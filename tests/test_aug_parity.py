"""Statistical parity of the on-device augmentations vs the reference's
PIL/torchvision semantics (`moco/loader.py`, `main_moco.py:~L225-255`).

torchvision itself is not installed in this image, so the oracles are
independent numpy/PIL re-statements of the documented torchvision
algorithms (RandomResizedCrop.get_params' 10-attempt rejection loop,
ImageEnhance blend formulas, uint8-HSV hue shift, ImageFilter blur).
Where our op is deliberately different (YIQ hue, true-Gaussian blur) the
test *bounds* the deviation instead of asserting equality, per VERDICT
round-1 item 5.
"""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from PIL import Image, ImageEnhance, ImageFilter
from scipy.stats import ks_2samp

from moco_tpu.data.augment import (
    adjust_brightness,
    adjust_contrast,
    adjust_hue,
    adjust_saturation,
    color_jitter,
    gaussian_blur,
    random_resized_crop_params,
)

# ------------------------------------------------------------------ RRC


def tv_rrc_params_oracle(rng: np.random.Generator, h, w, scale, ratio, n):
    """Sequential-loop restatement of torchvision
    RandomResizedCrop.get_params (transforms.py, 10-attempt rejection +
    ratio-clamped center-crop fallback)."""
    area = h * w
    out = np.zeros((n, 4))
    for s in range(n):
        for _ in range(10):
            ta = rng.uniform(scale[0], scale[1]) * area
            ar = math.exp(rng.uniform(math.log(ratio[0]), math.log(ratio[1])))
            cw = round(math.sqrt(ta * ar))
            ch = round(math.sqrt(ta / ar))
            if 0 < cw <= w and 0 < ch <= h:
                y0 = rng.integers(0, h - ch + 1)
                x0 = rng.integers(0, w - cw + 1)
                break
        else:
            in_ratio = w / h
            if in_ratio < ratio[0]:
                cw, ch = w, round(w / ratio[0])
            elif in_ratio > ratio[1]:
                ch, cw = h, round(h * ratio[1])
            else:
                cw, ch = w, h
            y0, x0 = (h - ch) // 2, (w - cw) // 2
        out[s] = (y0, x0, ch, cw)
    return out


class TestRRCDistribution:
    N = 8000

    @pytest.mark.parametrize(
        "h,w",
        [(64, 64), (48, 120)],  # square + wide (wide exercises rejections/fallback)
        ids=["square", "wide"],
    )
    def test_box_distribution_matches_torchvision(self, h, w):
        scale, ratio = (0.2, 1.0), (3 / 4, 4 / 3)
        ours = np.stack(
            jax.jit(
                lambda k: random_resized_crop_params(k, self.N, h, w, scale, ratio)
            )(jax.random.PRNGKey(3)),
            axis=1,
        )
        oracle = tv_rrc_params_oracle(np.random.default_rng(7), h, w, scale, ratio, self.N)
        # integer-valued boxes
        np.testing.assert_array_equal(ours, np.round(ours))
        # per-marginal two-sample KS on (y0, x0, ch, cw)
        for col, name in enumerate(["y0", "x0", "ch", "cw"]):
            stat = ks_2samp(ours[:, col], oracle[:, col]).statistic
            assert stat < 0.035, f"{name}: KS={stat:.4f} (h={h}, w={w})"
        # joint sanity: crop areas agree in mean within 2%
        area_ours = (ours[:, 2] * ours[:, 3]).mean()
        area_orc = (oracle[:, 2] * oracle[:, 3]).mean()
        assert abs(area_ours - area_orc) / area_orc < 0.02

    def test_boxes_always_inside_image(self):
        h, w = 40, 100
        y0, x0, ch, cw = random_resized_crop_params(
            jax.random.PRNGKey(0), 4096, h, w, (0.2, 1.0), (3 / 4, 4 / 3)
        )
        assert float((y0 >= 0).all()) and float((x0 >= 0).all())
        assert float(((y0 + ch) <= h).all()) and float(((x0 + cw) <= w).all())
        assert float((ch > 0).all()) and float((cw > 0).all())

    def test_fallback_is_ratio_clamped_center_crop(self):
        # scale forces boxes taller than the image → all 10 attempts reject
        # (H=8, W=256: any aspect ≤ 4/3 at area ≥ 0.9·A needs ch ≥ 37 > 8)
        h, w = 8, 256
        y0, x0, ch, cw = random_resized_crop_params(
            jax.random.PRNGKey(1), 64, h, w, (0.9, 1.0), (3 / 4, 4 / 3)
        )
        # in_ratio = 32 > 4/3 → fallback ch = h, cw = round(h * 4/3)
        np.testing.assert_array_equal(np.asarray(ch), h)
        np.testing.assert_array_equal(np.asarray(cw), round(h * 4 / 3))
        np.testing.assert_array_equal(np.asarray(y0), 0)
        np.testing.assert_array_equal(np.asarray(x0), (w - round(h * 4 / 3)) // 2)


# --------------------------------------------------------------- jitter


class TestJitterPerImageOrder:
    def test_matches_per_image_composition(self):
        """color_jitter == applying the four adjusts in each image's drawn
        order — recomputes the internal RNG splits and replays the exact
        composition per image."""
        rng = jax.random.PRNGKey(11)
        b, hue = 6, 0.1
        images = jax.random.uniform(jax.random.PRNGKey(5), (b, 12, 12, 3))
        out = color_jitter(rng, images, 0.4, 0.4, 0.4, hue, apply_prob=1.0)

        k_order, _, kb, kc, ks, kh = jax.random.split(rng, 6)
        fb = jax.random.uniform(kb, (b, 1, 1, 1), minval=0.6, maxval=1.4)
        fc = jax.random.uniform(kc, (b, 1, 1, 1), minval=0.6, maxval=1.4)
        fs = jax.random.uniform(ks, (b, 1, 1, 1), minval=0.6, maxval=1.4)
        fh = jax.random.uniform(kh, (b, 1, 1, 1), minval=-hue, maxval=hue)
        order = np.asarray(jnp.argsort(jax.random.uniform(k_order, (b, 4)), axis=1))

        adjusts = [adjust_brightness, adjust_contrast, adjust_saturation, adjust_hue]
        factors = [fb, fc, fs, fh]
        for i in range(b):
            x = images[i : i + 1]
            for op in order[i]:
                x = adjusts[op](x, factors[op][i : i + 1])
            np.testing.assert_allclose(np.asarray(out[i]), np.asarray(x[0]), atol=1e-5)

    def test_order_varies_across_images(self):
        orders = jnp.argsort(
            jax.random.uniform(jax.random.split(jax.random.PRNGKey(2), 1)[0], (64, 4)),
            axis=1,
        )
        assert len({tuple(np.asarray(o)) for o in orders}) > 1


# ----------------------------------------------------- PIL color parity


def _pil_roundtrip(img01: np.ndarray, fn) -> np.ndarray:
    pil = Image.fromarray((img01 * 255).round().astype(np.uint8))
    return np.asarray(fn(pil), np.float32) / 255.0


@pytest.fixture(scope="module")
def img01():
    rng = np.random.default_rng(0)
    # smooth-ish structured image: random low-freq field, upsampled
    small = rng.uniform(size=(8, 8, 3)).astype(np.float32)
    img = np.asarray(
        jax.image.resize(jnp.asarray(small), (64, 64, 3), "linear"), np.float32
    )
    return np.clip(img, 0.0, 1.0)


class TestPILColorParity:
    @pytest.mark.parametrize("factor", [0.6, 1.0, 1.4])
    def test_brightness(self, img01, factor):
        ours = np.asarray(adjust_brightness(jnp.asarray(img01)[None], jnp.full((1, 1, 1, 1), factor)))[0]
        want = _pil_roundtrip(img01, lambda im: ImageEnhance.Brightness(im).enhance(factor))
        assert np.abs(ours - want).mean() < 2 / 255
        assert np.abs(ours - want).max() < 4 / 255

    @pytest.mark.parametrize("factor", [0.6, 1.4])
    def test_saturation(self, img01, factor):
        ours = np.asarray(adjust_saturation(jnp.asarray(img01)[None], jnp.full((1, 1, 1, 1), factor)))[0]
        want = _pil_roundtrip(img01, lambda im: ImageEnhance.Color(im).enhance(factor))
        assert np.abs(ours - want).mean() < 2 / 255
        assert np.abs(ours - want).max() < 5 / 255

    @pytest.mark.parametrize("factor", [0.6, 1.4])
    def test_contrast(self, img01, factor):
        ours = np.asarray(adjust_contrast(jnp.asarray(img01)[None], jnp.full((1, 1, 1, 1), factor)))[0]
        want = _pil_roundtrip(img01, lambda im: ImageEnhance.Contrast(im).enhance(factor))
        # PIL computes the gray pivot from the rounded uint8 L-histogram
        # mean; allow that quantization plus blend rounding.
        assert np.abs(ours - want).mean() < 3 / 255
        assert np.abs(ours - want).max() < 6 / 255

    @pytest.mark.parametrize("delta", [-0.1, 0.1])
    def test_hue_bounded_vs_pil_hsv(self, img01, delta):
        """Float-HSV hue shift vs PIL's uint8 HSV shift (torchvision's
        PIL backend): same color model, so the residual is PIL's uint8
        quantization (~1-2/255). This test caught a wrong-direction YIQ
        rotation (0.17 mean abs) in an earlier implementation."""
        ours = np.asarray(adjust_hue(jnp.asarray(img01)[None], jnp.full((1, 1, 1, 1), delta)))[0]

        def pil_hue(im):
            h, s, v = im.convert("HSV").split()
            shift = int(round(delta * 255))
            h = h.point(lambda px: (px + shift) % 256)
            return Image.merge("HSV", (h, s, v)).convert("RGB")

        want = _pil_roundtrip(img01, pil_hue)
        assert np.abs(ours - want).mean() < 0.008
        assert np.abs(ours - want).max() < 0.05


# ------------------------------------------------------- PIL blur parity


class TestPILBlurParity:
    @pytest.mark.parametrize("sigma", [0.5, 1.5, 2.0])
    def test_blur_bounded_vs_pil(self, img01, sigma):
        """Reference blur is PIL ImageFilter.GaussianBlur(radius=sigma)
        (`moco/loader.py:~L23-35`). Ours is an exact truncated Gaussian;
        PIL's is its own windowed implementation — bound the gap."""
        ours = np.asarray(
            gaussian_blur(
                jax.random.PRNGKey(0),
                jnp.asarray(img01)[None],
                sigma_range=(sigma, sigma),
                apply_prob=1.0,
            )
        )[0]
        want = _pil_roundtrip(img01, lambda im: im.filter(ImageFilter.GaussianBlur(sigma)))
        # interior only: PIL pads by edge replication too but with its own
        # window; borders carry the largest discrepancy
        c = 4
        diff = np.abs(ours - want)[c:-c, c:-c]
        assert diff.mean() < 2 / 255
        assert diff.max() < 8 / 255


class TestHostRRCSampler:
    """numpy twin of the jax sampler (host-crop pipeline) against the
    same sequential torchvision oracle."""

    N = 8000

    @pytest.mark.parametrize("h,w", [(64, 64), (48, 120)], ids=["square", "wide"])
    def test_matches_oracle(self, h, w):
        from moco_tpu.data.datasets import sample_rrc_boxes

        scale, ratio = (0.2, 1.0), (3 / 4, 4 / 3)
        dims = np.full((self.N, 2), (h, w), np.int32)
        ours = sample_rrc_boxes(np.random.default_rng(11), dims, scale, ratio)
        oracle = tv_rrc_params_oracle(np.random.default_rng(7), h, w, scale, ratio, self.N)
        for col, name in enumerate(["y0", "x0", "ch", "cw"]):
            stat = ks_2samp(ours[:, col], oracle[:, col]).statistic
            assert stat < 0.035, f"{name}: KS={stat:.4f} (h={h}, w={w})"

    def test_boxes_inside_per_image_dims(self):
        from moco_tpu.data.datasets import sample_rrc_boxes

        rng = np.random.default_rng(0)
        dims = rng.integers(20, 200, (4096, 2)).astype(np.int32)
        b = sample_rrc_boxes(rng, dims)
        assert (b[:, 0] >= 0).all() and (b[:, 1] >= 0).all()
        assert (b[:, 0] + b[:, 2] <= dims[:, 0]).all()
        assert (b[:, 1] + b[:, 3] <= dims[:, 1]).all()
        assert (b[:, 2] > 0).all() and (b[:, 3] > 0).all()
