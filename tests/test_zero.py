"""Sharded weight update (ZeRO-1 over the data axis — parallel/zero.py,
after arXiv:2004.13336): the sharded step must produce EXACTLY the same
training trajectory as the replicated update, with opt state held as
(n, m) shards."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from moco_tpu.core import build_encoder, build_predictor, create_state, make_train_step, place_state
from moco_tpu.parallel import create_mesh, shard_batch
from moco_tpu.utils.config import (
    DataConfig,
    MocoConfig,
    OptimConfig,
    ParallelConfig,
    TrainConfig,
)
from moco_tpu.utils.schedules import build_optimizer

IMG, BATCH = 16, 16


def _config(zero: bool, optimizer: str = "sgd", v3: bool = False) -> TrainConfig:
    return TrainConfig(
        moco=MocoConfig(
            arch="resnet18" if not v3 else "vit_tiny",
            dim=32,
            num_negatives=0 if v3 else 256,
            momentum=0.99,
            temperature=0.2,
            mlp=not v3,
            v3=v3,
            shuffle="none" if v3 else "gather_perm",
            cifar_stem=True,
            compute_dtype="float32",
            vit_patch_size=4 if v3 else None,
        ),
        optim=OptimConfig(
            optimizer=optimizer,
            lr=0.05 if optimizer == "sgd" else 1e-3,
            weight_decay=1e-4 if optimizer == "sgd" else 0.1,
            epochs=2,
            cos=True,
        ),
        data=DataConfig(dataset="synthetic", image_size=IMG, global_batch=BATCH),
        parallel=ParallelConfig(num_data=8, shard_weight_update=zero),
    )


def _run_steps(config: TrainConfig, n_steps: int = 2):
    mesh = create_mesh(num_data=8)
    encoder = build_encoder(config.moco, num_data=8)
    predictor = build_predictor(config.moco, num_data=8)
    tx = build_optimizer(config.optim, steps_per_epoch=4)
    sample = jnp.zeros((1, IMG, IMG, 3), jnp.float32)
    zero = config.parallel.shard_weight_update
    state = create_state(
        jax.random.PRNGKey(0), config, encoder, tx, sample, predictor=predictor,
        zero_num_data=8 if zero else None,
    )
    step = make_train_step(
        config, encoder, tx, mesh, predictor=predictor, total_steps=8,
        state_template=state if zero else None,
    )
    state = place_state(state, mesh, zero=zero)
    rng = jax.device_put(
        jax.random.PRNGKey(3),
        jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec()),
    )
    losses = []
    for i in range(n_steps):
        ims = jax.random.normal(jax.random.PRNGKey(10 + i), (2, BATCH, IMG, IMG, 3))
        batch = shard_batch(mesh, {"im_q": ims[0], "im_k": ims[1]})
        state, metrics = step(state, batch, rng)
        losses.append(float(metrics["loss"]))
    return state, losses


@pytest.mark.parametrize("optimizer", ["sgd", "adamw"])
@pytest.mark.slow  # replicated-vs-ZeRO A/B compiles both step programs per optimizer
def test_zero_matches_replicated_update(optimizer):
    s_rep, l_rep = _run_steps(_config(zero=False, optimizer=optimizer))
    s_zero, l_zero = _run_steps(_config(zero=True, optimizer=optimizer))
    np.testing.assert_allclose(l_zero, l_rep, rtol=1e-5)
    for a, b in zip(jax.tree.leaves(s_rep.params_q), jax.tree.leaves(s_zero.params_q)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6)


@pytest.mark.slow  # compiles two full v3 steps over the mesh (~1 min on CPU)
def test_zero_v3_step_runs_and_matches():
    s_rep, l_rep = _run_steps(_config(zero=False, optimizer="adamw", v3=True))
    s_zero, l_zero = _run_steps(_config(zero=True, optimizer="adamw", v3=True))
    np.testing.assert_allclose(l_zero, l_rep, rtol=1e-5)
    # frozen patch embed must stay at init under ZeRO too
    pe_rep = jax.tree.leaves(s_rep.params_q["backbone"]["patch_embed"])
    pe_zero = jax.tree.leaves(s_zero.params_q["backbone"]["patch_embed"])
    for a, b in zip(pe_rep, pe_zero):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_zero_opt_state_is_sharded():
    config = _config(zero=True, optimizer="adamw")
    # opt state leaves (other than scalars) are (8, m): 1/8 per device
    state, _ = _run_steps(config, n_steps=1)
    leaves = [x for x in jax.tree.leaves(state.opt_state) if x.ndim == 2]
    assert leaves, "expected sharded (n, m) opt-state leaves"
    for leaf in leaves:
        assert leaf.shape[0] == 8
        assert len(leaf.addressable_shards) == 8
        assert leaf.addressable_shards[0].data.shape[0] == 1  # one row per device


def test_zero_rejects_lars():
    config = _config(zero=True, optimizer="sgd")
    config = dataclasses.replace(
        config, optim=dataclasses.replace(config.optim, optimizer="lars")
    )
    mesh = create_mesh(num_data=8)
    encoder = build_encoder(config.moco, num_data=8)
    tx = build_optimizer(config.optim, steps_per_epoch=4)
    state = create_state(
        jax.random.PRNGKey(0), config, encoder, tx,
        jnp.zeros((1, IMG, IMG, 3), jnp.float32), zero_num_data=8,
    )
    with pytest.raises(ValueError, match="element-wise"):
        make_train_step(config, encoder, tx, mesh, state_template=state)


@pytest.mark.slow  # full step + probe-surgery chain
def test_zero_checkpoint_restores_into_lincls(tmp_path):
    """A ZeRO-trained checkpoint must restore through the downstream
    template builders: the driver records the train-time mesh width in
    extras, and load_pretrained_backbone rebuilds the (num_data, m)
    opt-state layout from it (regression: it used to build a replicated
    template and fail the StandardRestore shape match)."""
    from moco_tpu.data.datasets import SyntheticDataset
    from moco_tpu.lincls import load_pretrained_backbone
    from moco_tpu.train import train

    config = _config(zero=True, optimizer="adamw")
    config = dataclasses.replace(
        config,
        optim=dataclasses.replace(config.optim, epochs=1),
        workdir=str(tmp_path / "pre_zero"),
        log_every=100,
    )
    dataset = SyntheticDataset(num_examples=2 * BATCH, image_size=IMG)
    train(config, dataset=dataset)

    # config=None: arch/optimizer/ZeRO layout all come from the checkpoint
    params, stats, cfg = load_pretrained_backbone(config.workdir)
    assert cfg.parallel.shard_weight_update
    assert jax.tree.leaves(params)
