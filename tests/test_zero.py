"""Sharded weight update (ZeRO over the data axis — parallel/zero.py,
after arXiv:2004.13336): the sharded step must produce EXACTLY the same
training trajectory as the replicated update, with opt state held as
(n, m) shards — and, at stage 2/3, the params themselves persisting as
shards with bucketed collectives, BIT-identical to stage 1."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from moco_tpu.core import (
    build_encoder,
    build_predictor,
    create_state,
    full_param_shapes,
    make_train_step,
    place_state,
    reshard_state,
)
from moco_tpu.parallel import create_mesh, shard_batch
from moco_tpu.parallel.zero import (
    AsyncParamGather,
    BucketPlan,
    unshard_tree_host,
)
from moco_tpu.utils.config import (
    DataConfig,
    MocoConfig,
    OptimConfig,
    ParallelConfig,
    TrainConfig,
)
from moco_tpu.utils.schedules import build_optimizer

IMG, BATCH = 16, 16


def _config(
    zero: bool,
    optimizer: str = "sgd",
    v3: bool = False,
    stage: int = 1,
    layer: bool = False,
) -> TrainConfig:
    return TrainConfig(
        moco=MocoConfig(
            arch="resnet18" if not v3 else "vit_tiny",
            dim=32,
            num_negatives=0 if v3 else 256,
            momentum=0.99,
            temperature=0.2,
            mlp=not v3,
            v3=v3,
            shuffle="none" if v3 else "gather_perm",
            cifar_stem=True,
            compute_dtype="float32",
            vit_patch_size=4 if v3 else None,
        ),
        optim=OptimConfig(
            optimizer=optimizer,
            lr=0.05 if optimizer == "sgd" else 1e-3,
            weight_decay=1e-4 if optimizer == "sgd" else 0.1,
            epochs=2,
            cos=True,
        ),
        data=DataConfig(dataset="synthetic", image_size=IMG, global_batch=BATCH),
        parallel=ParallelConfig(
            num_data=8, shard_weight_update=zero, zero_stage=stage,
            # tiny fusion buckets so even the toy model exercises
            # multi-bucket packing (and the ragged tail)
            zero_bucket_mb=0.002,
            zero_layer_granular=layer,
        ),
    )


def _run_steps(config: TrainConfig, n_steps: int = 2, return_step: bool = False):
    mesh = create_mesh(num_data=8)
    encoder = build_encoder(config.moco, num_data=8)
    predictor = build_predictor(config.moco, num_data=8)
    tx = build_optimizer(config.optim, steps_per_epoch=4)
    sample = jnp.zeros((1, IMG, IMG, 3), jnp.float32)
    zero = config.parallel.shard_weight_update
    state = create_state(
        jax.random.PRNGKey(0), config, encoder, tx, sample, predictor=predictor,
        zero_num_data=8 if zero else None,
    )
    step = make_train_step(
        config, encoder, tx, mesh, predictor=predictor, total_steps=8,
        state_template=state if zero else None,
    )
    state = place_state(
        state, mesh, zero=zero,
        zero_params=zero and config.parallel.zero_stage >= 2,
    )
    rng = jax.device_put(
        jax.random.PRNGKey(3),
        jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec()),
    )
    losses = []
    for i in range(n_steps):
        ims = jax.random.normal(jax.random.PRNGKey(10 + i), (2, BATCH, IMG, IMG, 3))
        batch = shard_batch(mesh, {"im_q": ims[0], "im_k": ims[1]})
        state, metrics = step(state, batch, rng)
        losses.append(float(metrics["loss"]))
    if return_step:
        return state, losses, step
    return state, losses


@pytest.mark.parametrize("optimizer", ["sgd", "adamw"])
@pytest.mark.slow  # replicated-vs-ZeRO A/B compiles both step programs per optimizer
def test_zero_matches_replicated_update(optimizer):
    s_rep, l_rep = _run_steps(_config(zero=False, optimizer=optimizer))
    s_zero, l_zero = _run_steps(_config(zero=True, optimizer=optimizer))
    np.testing.assert_allclose(l_zero, l_rep, rtol=1e-5)
    for a, b in zip(jax.tree.leaves(s_rep.params_q), jax.tree.leaves(s_zero.params_q)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6)


@pytest.mark.slow  # compiles two full v3 steps over the mesh (~1 min on CPU)
def test_zero_v3_step_runs_and_matches():
    s_rep, l_rep = _run_steps(_config(zero=False, optimizer="adamw", v3=True))
    s_zero, l_zero = _run_steps(_config(zero=True, optimizer="adamw", v3=True))
    np.testing.assert_allclose(l_zero, l_rep, rtol=1e-5)
    # frozen patch embed must stay at init under ZeRO too
    pe_rep = jax.tree.leaves(s_rep.params_q["backbone"]["patch_embed"])
    pe_zero = jax.tree.leaves(s_zero.params_q["backbone"]["patch_embed"])
    for a, b in zip(pe_rep, pe_zero):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_zero_opt_state_is_sharded():
    config = _config(zero=True, optimizer="adamw")
    # opt state leaves (other than scalars) are (8, m): 1/8 per device
    state, _ = _run_steps(config, n_steps=1)
    leaves = [x for x in jax.tree.leaves(state.opt_state) if x.ndim == 2]
    assert leaves, "expected sharded (n, m) opt-state leaves"
    for leaf in leaves:
        assert leaf.shape[0] == 8
        assert len(leaf.addressable_shards) == 8
        assert leaf.addressable_shards[0].data.shape[0] == 1  # one row per device


@pytest.mark.parametrize("stage", [1, 3])
def test_zero_rejects_lars(stage):
    config = _config(zero=True, optimizer="sgd", stage=stage)
    config = dataclasses.replace(
        config, optim=dataclasses.replace(config.optim, optimizer="lars")
    )
    mesh = create_mesh(num_data=8)
    encoder = build_encoder(config.moco, num_data=8)
    tx = build_optimizer(config.optim, steps_per_epoch=4)
    state = create_state(
        jax.random.PRNGKey(0), config, encoder, tx,
        jnp.zeros((1, IMG, IMG, 3), jnp.float32), zero_num_data=8,
    )
    with pytest.raises(ValueError, match="element-wise"):
        make_train_step(config, encoder, tx, mesh, state_template=state)


# ---------------------------------------------------------------------------
# ZeRO-2/3: persistently sharded params + bucketed collectives (ISSUE 7)
# ---------------------------------------------------------------------------


def test_zero23_update_bit_identical_to_zero1():
    """The stage-2/3 step (persistent shards, bucketed collectives,
    gather-at-step-start, shard-local EMA) must be BIT-identical to the
    validated stage-1 sharded update: the bucket transforms preserve
    per-leaf partitioning, so every reduction runs in the same order.
    (Stage 1 itself matches the replicated update to float tolerance —
    test_zero_matches_replicated_update — psum vs psum_scatter reduce
    in different orders, so bitwise equality across THAT boundary is
    not expected.)"""
    s1, l1 = _run_steps(_config(zero=True), n_steps=2)
    s23, l23 = _run_steps(_config(zero=True, stage=3), n_steps=2)
    assert l1 == l23, f"loss trajectories diverged: {l1} vs {l23}"
    cfg = _config(zero=True, stage=3)
    shapes = full_param_shapes(cfg, build_encoder(cfg.moco, num_data=8))
    q_full = unshard_tree_host(s23.params_q, shapes["enc"])
    k_full = unshard_tree_host(s23.params_k, shapes["enc"])
    for a, b in zip(jax.tree.leaves(s1.params_q), jax.tree.leaves(q_full)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(jax.tree.leaves(s1.params_k), jax.tree.leaves(k_full)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # opt state shares the (n, m) layout across stages: directly bitwise
    for a, b in zip(jax.tree.leaves(s1.opt_state), jax.tree.leaves(s23.opt_state)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # ... and the stage-2/3 params PERSIST as (8, m), one row per device,
    # shrinking the at-rest per-device state footprint (same runs reused
    # so the suite pays no extra compiles for the layout assertions)
    from moco_tpu.obs.stepstats import tree_shard_bytes

    for leaf in jax.tree.leaves(s23.params_q):
        assert leaf.ndim == 2 and leaf.shape[0] == 8
        assert len(leaf.addressable_shards) == 8
        assert leaf.addressable_shards[0].data.shape[0] == 1
    assert tree_shard_bytes(s23) < 0.5 * tree_shard_bytes(s1)


def test_zero_layer_granular_bit_identical_and_peak():
    """Tentpole invariant (ISSUE 20): the layer-granular schedule —
    per-group just-in-time gathers inside rematerialized segments, one
    group prefetched ahead, AD-transpose psum_scatter landing summed
    cotangents on the shards — reproduces the whole-tree stage-2/3 step
    BIT-identically on ResNet (losses, params, opt state, both stats
    collections), while the analytic peak model bytes drop >= 2x below
    the whole-tree gather's."""
    s23, l23, st23 = _run_steps(_config(zero=True, stage=3), return_step=True)
    sl, ll, stl = _run_steps(
        _config(zero=True, stage=3, layer=True), return_step=True
    )
    assert l23 == ll, f"loss trajectories diverged: {l23} vs {ll}"
    cfg = _config(zero=True, stage=3)
    shapes = full_param_shapes(cfg, build_encoder(cfg.moco, num_data=8))
    for name in ("params_q", "params_k"):
        a = unshard_tree_host(getattr(s23, name), shapes["enc"])
        b = unshard_tree_host(getattr(sl, name), shapes["enc"])
        for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
    for x, y in zip(jax.tree.leaves(s23.opt_state), jax.tree.leaves(sl.opt_state)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
    for coll in ("batch_stats_q", "batch_stats_k"):
        for x, y in zip(
            jax.tree.leaves(getattr(s23, coll)), jax.tree.leaves(getattr(sl, coll))
        ):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
    # the memory claim, analytically: shards + one live group pair vs
    # shards + the whole gathered tree
    assert stl.layer_granular and not st23.layer_granular
    assert stl.hbm_model_peak_bytes * 2 <= st23.hbm_model_peak_bytes, (
        f"layer-granular peak {stl.hbm_model_peak_bytes} not >=2x below "
        f"whole-tree {st23.hbm_model_peak_bytes}"
    )
    # the schedule is the model's declared group order
    assert [g.name for g in stl.group_plan.groups] == list(
        build_encoder(cfg.moco, num_data=8).backbone.group_names
    ) + ["head"]


@pytest.mark.slow  # two extra v3 step compiles (ViT + predictor path)
def test_zero_layer_granular_v3_loss_bitwise():
    """The v3 (ViT + predictor) layer schedule: loss trajectory bitwise
    vs whole-tree zero23. Params are NOT asserted bitwise here:
    `jax.checkpoint` alone shifts ViT backward gradients by ~1e-9 on CPU
    (XLA fuses the rematerialized backward differently), and adamw's
    sign-like step-1 normalization amplifies that — see the note in
    core/moco.py's `_make_q_segment`."""
    _, l23 = _run_steps(_config(zero=True, stage=3, v3=True, optimizer="adamw"))
    _, ll = _run_steps(
        _config(zero=True, stage=3, v3=True, optimizer="adamw", layer=True)
    )
    assert l23 == ll, f"v3 loss trajectories diverged: {l23} vs {ll}"


def test_zero_layer_granular_requires_stage23():
    """The layer flag without persistent param shards is a config error,
    not a silent fallback."""
    config = _config(zero=True, stage=1, layer=True)
    mesh = create_mesh(num_data=8)
    encoder = build_encoder(config.moco, num_data=8)
    tx = build_optimizer(config.optim, steps_per_epoch=4)
    state = create_state(
        jax.random.PRNGKey(0), config, encoder, tx,
        jnp.zeros((1, IMG, IMG, 3), jnp.float32), zero_num_data=8,
    )
    with pytest.raises(ValueError, match="zero_layer_granular"):
        make_train_step(config, encoder, tx, mesh, state_template=state)


def test_zero_layer_step_donates_shards():
    """Donation audit: with donate=True the layer-granular step consumes
    the input state's shard buffers (no silent double-buffering of the
    persistent (n, m) shards next to the per-group transients)."""
    config = _config(zero=True, stage=3, layer=True)
    mesh = create_mesh(num_data=8)
    encoder = build_encoder(config.moco, num_data=8)
    tx = build_optimizer(config.optim, steps_per_epoch=4)
    state = create_state(
        jax.random.PRNGKey(0), config, encoder, tx,
        jnp.zeros((1, IMG, IMG, 3), jnp.float32), zero_num_data=8,
    )
    step = make_train_step(
        config, encoder, tx, mesh, total_steps=8, state_template=state,
        donate=True,
    )
    state = place_state(state, mesh, zero=True, zero_params=True)
    rng = jax.device_put(
        jax.random.PRNGKey(3),
        jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec()),
    )
    ims = jax.random.normal(jax.random.PRNGKey(10), (2, BATCH, IMG, IMG, 3))
    batch = shard_batch(mesh, {"im_q": ims[0], "im_k": ims[1]})
    old_params = jax.tree.leaves(state.params_q)
    new_state, _ = step(state, batch, rng)
    jax.block_until_ready(new_state.params_q)
    assert all(x.is_deleted() for x in old_params), "input shards not donated"


def test_bucket_plan_packing_ragged_tail():
    """Greedy per-dtype packing: buckets close at the byte threshold,
    the ragged tail leaf lands in a final smaller bucket, every leaf is
    covered exactly once with contiguous offsets."""
    n = 8
    leaves = [
        jax.ShapeDtypeStruct((1000,), jnp.float32),  # m=125, 500B shard
        jax.ShapeDtypeStruct((1000,), jnp.float32),
        jax.ShapeDtypeStruct((1000,), jnp.float32),
        jax.ShapeDtypeStruct((7,), jnp.float32),  # the ragged tail
    ]
    plan = BucketPlan(leaves, n, bucket_bytes=1000)
    assert len(plan.buckets) == 2
    covered = sorted(s.index for b in plan.buckets for s in b.slots)
    assert covered == [0, 1, 2, 3]
    for b in plan.buckets:
        off = 0
        for s in b.slots:
            assert s.offset == off
            off += s.m
        assert off == b.total_m
    # the tail bucket holds the leftover leaf 2 + the tiny leaf 3
    tail = plan.buckets[-1]
    assert {s.index for s in tail.slots} == {2, 3}
    assert tail.slots[-1].m == 1  # padded_cols(7, 8)


def test_bucket_plan_splits_dtypes():
    n = 8
    leaves = [
        jax.ShapeDtypeStruct((64,), jnp.float32),
        jax.ShapeDtypeStruct((64,), jnp.int32),
        jax.ShapeDtypeStruct((64,), jnp.float32),
    ]
    plan = BucketPlan(leaves, n, bucket_bytes=1 << 20)
    assert len(plan.buckets) == 2  # one open bucket per dtype
    by_dtype = {str(b.dtype): {s.index for s in b.slots} for b in plan.buckets}
    assert by_dtype["float32"] == {0, 2}
    assert by_dtype["int32"] == {1}


def test_group_plan_partition_errors_and_peak():
    """GroupPlan construction is a total partition check: overlapping
    and missing leaves are errors at build time, and peak_full_bytes is
    the largest ADJACENT pair (the one-group-ahead liveness bound), not
    the largest single group or the total."""
    from moco_tpu.parallel.zero import GroupPlan

    leaves = [
        jax.ShapeDtypeStruct((64,), jnp.float32),  # 256 B
        jax.ShapeDtypeStruct((32,), jnp.float32),  # 128 B
        jax.ShapeDtypeStruct((128,), jnp.float32),  # 512 B
        jax.ShapeDtypeStruct((8,), jnp.float32),  # 32 B
    ]
    with pytest.raises(ValueError, match="re-claims"):
        GroupPlan(leaves, [("a", (0, 1)), ("b", (1, 2, 3))], n=8)
    with pytest.raises(ValueError, match="misses"):
        GroupPlan(leaves, [("a", (0, 1)), ("b", (3,))], n=8)
    plan = GroupPlan(leaves, [("a", (0,)), ("b", (1, 2)), ("c", (3,))], n=8)
    assert [g.name for g in plan.groups] == ["a", "b", "c"]
    assert [g.full_bytes for g in plan.groups] == [256, 640, 32]
    assert plan.peak_full_bytes() == 256 + 640  # adjacent pair a+b
    assert plan.total_full_bytes() == 928
    assert [d["group"] for d in plan.describe()] == ["a", "b", "c"]
    # single-group degenerate case: the peak is the group itself
    solo = GroupPlan(leaves[:1], [("only", (0,))], n=8)
    assert solo.peak_full_bytes() == 256


def test_group_plan_gather_matches_whole_tree_gather():
    """Per-group bucketed gathers reassemble EXACTLY the same full
    leaves as one whole-tree BucketPlan gather (and the source values):
    the element->chunk assignment invariant extends across the group
    partition, so the layer schedule changes memory, not bits."""
    from moco_tpu.parallel.compat import shard_map
    from moco_tpu.parallel.zero import GroupPlan

    P = jax.sharding.PartitionSpec
    n = 8
    rng = np.random.default_rng(0)
    full = [
        jnp.asarray(rng.standard_normal(s).astype(np.float32))
        for s in ((40,), (33,), (8, 8), (5,))
    ]
    descs = [jax.ShapeDtypeStruct(x.shape, x.dtype) for x in full]
    whole = BucketPlan(descs, n, bucket_bytes=128)
    gp = GroupPlan(descs, [("a", (0, 1)), ("b", (2, 3))], n, bucket_bytes=128)
    sharded = whole.shard_leaves(full)  # (n, m) rows, shared layout

    def run(*rows):
        loc = [r.reshape(-1) for r in rows]
        out_whole = whole.gather(loc, site="test.zero.gather")
        ga = gp.gather_group(gp.group_shards(loc, 0), 0, site_prefix="test.zero.layer")
        gb = gp.gather_group(gp.group_shards(loc, 1), 1, site_prefix="test.zero.layer")
        return tuple(out_whole), tuple(ga + gb)

    mesh = create_mesh(num_data=n)
    f = jax.jit(
        shard_map(
            run,
            mesh=mesh,
            in_specs=tuple(P("data") for _ in sharded),
            out_specs=(tuple(P() for _ in full), tuple(P() for _ in full)),
            check_vma=False,
        )
    )
    out_whole, out_groups = f(*sharded)
    for src, w, g in zip(full, out_whole, out_groups):
        np.testing.assert_array_equal(np.asarray(w), np.asarray(src))
        np.testing.assert_array_equal(np.asarray(g), np.asarray(src))


def test_reshard_state_layout_roundtrips():
    """Host-side layout conversion (the 'compatible but resharded'
    resume): zero1 -> zero23 and zero23 -> replicated both reproduce a
    directly-created state of the target layout, bit-for-bit — no step
    compile needed, the init values make the comparison exact."""
    cfg_rep = _config(zero=False)
    cfg_z1 = _config(zero=True, stage=1)
    cfg_z23 = _config(zero=True, stage=3)
    encoder = build_encoder(cfg_rep.moco, num_data=8)
    tx = build_optimizer(cfg_z1.optim, steps_per_epoch=4)
    sample = jnp.zeros((1, IMG, IMG, 3), jnp.float32)
    rng = jax.random.PRNGKey(0)
    s_rep = create_state(rng, cfg_rep, encoder, tx, sample)
    s_z1 = create_state(rng, cfg_z1, encoder, tx, sample, zero_num_data=8)  # mocolint: disable=JX003  (same seed on purpose: the three layouts must hold identical values for the bitwise comparison)
    s_z23 = create_state(rng, cfg_z23, encoder, tx, sample, zero_num_data=8)  # mocolint: disable=JX003  (same seed on purpose, see above)

    up = reshard_state(s_z1, live_template=s_z23, full_template=s_rep)
    for a, b in zip(jax.tree.leaves(up.params_q), jax.tree.leaves(s_z23.params_q)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(jax.tree.leaves(up.opt_state), jax.tree.leaves(s_z23.opt_state)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    down = reshard_state(s_z23, live_template=s_rep, full_template=s_rep)
    for a, b in zip(jax.tree.leaves(down.params_q), jax.tree.leaves(s_rep.params_q)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(jax.tree.leaves(down.params_k), jax.tree.leaves(s_rep.params_k)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_reshard_state_unequal_mesh_widths():
    """The elastic-rescale conversion path (ISSUE 12): a checkpoint's
    (n, m) flat shards restore onto a NARROWER, non-divisor mesh width —
    8 -> 5 -> 3 — through the flat-vector converter, bit-for-bit. The
    queue rows, pointer, and batch stats pass through untouched (they
    are replicated, width-independent), and every opt-state leaf lands
    exactly as a directly-created state of the target width would."""
    widths = (8, 5, 3)
    cfg = {n: _config(zero=True, stage=3) for n in widths}
    encoder = build_encoder(cfg[8].moco, num_data=8)
    tx = build_optimizer(cfg[8].optim, steps_per_epoch=4)
    sample = jnp.zeros((1, IMG, IMG, 3), jnp.float32)
    rng = jax.random.PRNGKey(0)
    s_rep = create_state(rng, _config(zero=False), encoder, tx, sample)
    states = {
        n: create_state(rng, cfg[n], encoder, tx, sample, zero_num_data=n)  # mocolint: disable=JX003  (same seed on purpose: every width must hold identical values for the bitwise cross-width comparison)
        for n in widths
    }
    # make the queue content distinctive so "passes through" is a real check
    marked = jnp.arange(states[8].queue.size, dtype=jnp.float32).reshape(
        states[8].queue.shape
    )
    states = {
        n: s.replace(queue=marked, queue_ptr=jnp.asarray(7, jnp.int32))
        for n, s in states.items()
    }

    def assert_matches(converted, target):
        for name in ("params_q", "params_k", "opt_state"):
            for a, b in zip(
                jax.tree.leaves(getattr(converted, name)),
                jax.tree.leaves(getattr(target, name)),
            ):
                np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        np.testing.assert_array_equal(
            np.asarray(converted.queue), np.asarray(target.queue)
        )
        assert int(converted.queue_ptr) == int(target.queue_ptr)

    down_5 = reshard_state(states[8], live_template=states[5], full_template=s_rep)
    assert_matches(down_5, states[5])
    down_3 = reshard_state(down_5, live_template=states[3], full_template=s_rep)
    assert_matches(down_3, states[3])
    # and back out to replicated: the full roundtrip loses nothing
    back = reshard_state(down_3, live_template=s_rep, full_template=s_rep)
    for a, b in zip(jax.tree.leaves(back.params_q), jax.tree.leaves(s_rep.params_q)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_reshard_layer_granular_roundtrips_and_resume_compat():
    """Satellite (ISSUE 20): the layer-granular stage rides the zero23
    persistent layout, so reshard_state round-trips zero1 <-> zero23 <->
    layer-granular bitwise (including across mesh widths 8 -> 5), and
    toggling `zero_layer_granular` across a resume is NOT a structural
    incompatibility (it is a schedule, not a layout)."""
    from moco_tpu.utils.config import config_to_dict, resume_compat_diff

    cfg_z1 = _config(zero=True, stage=1)
    cfg_layer = _config(zero=True, stage=3, layer=True)
    encoder = build_encoder(cfg_z1.moco, num_data=8)
    tx = build_optimizer(cfg_z1.optim, steps_per_epoch=4)
    sample = jnp.zeros((1, IMG, IMG, 3), jnp.float32)
    rng = jax.random.PRNGKey(0)
    s_rep = create_state(rng, _config(zero=False), encoder, tx, sample)
    s_z1 = create_state(rng, cfg_z1, encoder, tx, sample, zero_num_data=8)  # mocolint: disable=JX003  (same seed on purpose: bitwise layout roundtrip)
    s_layer = create_state(rng, cfg_layer, encoder, tx, sample, zero_num_data=8)  # mocolint: disable=JX003  (same seed on purpose, see above)
    s_layer5 = create_state(rng, cfg_layer, encoder, tx, sample, zero_num_data=5)  # mocolint: disable=JX003  (same seed on purpose, see above)

    up = reshard_state(s_z1, live_template=s_layer, full_template=s_rep)
    for a, b in zip(jax.tree.leaves(up.params_q), jax.tree.leaves(s_layer.params_q)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    narrow = reshard_state(up, live_template=s_layer5, full_template=s_rep)
    for a, b in zip(
        jax.tree.leaves(narrow.opt_state), jax.tree.leaves(s_layer5.opt_state)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    back = reshard_state(narrow, live_template=s_z1, full_template=s_rep)
    for a, b in zip(jax.tree.leaves(back.params_q), jax.tree.leaves(s_z1.params_q)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # resume-compat: the flag flip produces NO structural diff entries
    saved = {"config": config_to_dict(_config(zero=True, stage=3)), "num_data": 8}
    assert resume_compat_diff(saved, cfg_layer, num_data=8) == []


def test_embedding_index_rows_survive_width_shrink():
    """The dictionary side of the elastic shrink: EmbeddingIndex rows
    carried on an 8-wide mesh land bitwise-identical on a 5-wide (then
    3-wide) mesh, the valid-count mask still hides the capacity padding
    (which differs per width), and top-k retrieval returns the same
    neighbors after the move."""
    from moco_tpu.serve.index import EmbeddingIndex

    rng = np.random.default_rng(0)
    dim, valid = 16, 50
    rows = rng.standard_normal((valid, dim)).astype(np.float32)
    rows /= np.linalg.norm(rows, axis=1, keepdims=True)
    queries = rows[:4] + 0.01 * rng.standard_normal((4, dim)).astype(np.float32)
    queries = (queries / np.linalg.norm(queries, axis=1, keepdims=True)).astype(
        np.float32
    )

    results = {}
    for n in (8, 5, 3):
        mesh = create_mesh(num_data=n, num_model=1, devices=jax.devices()[:n])
        idx = EmbeddingIndex(capacity=valid + 3, dim=dim, mesh=mesh)
        # capacity pads up to the axis width, differently per width
        assert idx.capacity % n == 0 and idx.capacity >= valid + 3
        idx.snapshot(rows)
        assert idx.count == valid  # the valid-count mask, not the padding
        stored = np.asarray(idx.rows)[:valid]
        np.testing.assert_array_equal(stored, rows)  # bitwise row preservation
        assert not np.any(np.asarray(idx.rows)[valid:])  # padding stays zero
        idx.prepare(buckets=(4,), k=5)
        idx.freeze()
        _, ids = idx.query(queries, k=5)
        assert (ids < valid).all(), f"width {n} returned padded/invalid rows: {ids}"
        results[n] = ids
    np.testing.assert_array_equal(results[8], results[5])
    np.testing.assert_array_equal(results[5], results[3])


def test_zero23_eval_gather_matches_replicated_init():
    """The eval-side one-shot gather (unshard_tree_host): a freshly
    created stage-2/3 state gathers back to exactly the replicated
    init — the invariant export/knn/lincls rely on."""
    cfg = _config(zero=True, stage=3)
    encoder = build_encoder(cfg.moco, num_data=8)
    tx = build_optimizer(cfg.optim, steps_per_epoch=4)
    sample = jnp.zeros((1, IMG, IMG, 3), jnp.float32)
    rng = jax.random.PRNGKey(0)
    s_rep = create_state(rng, _config(zero=False), encoder, tx, sample)
    s_z = create_state(rng, cfg, encoder, tx, sample, zero_num_data=8)  # mocolint: disable=JX003  (same seed on purpose: gather must reproduce the replicated init bit-for-bit)
    shapes = full_param_shapes(cfg, encoder)
    gathered = unshard_tree_host(s_z.params_q, shapes["enc"])
    for a, b in zip(jax.tree.leaves(s_rep.params_q), jax.tree.leaves(gathered)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_async_param_gather_overlap_and_hygiene():
    """AsyncParamGather unit: the gather is DISPATCHED on the caller's
    thread (submit calls gather_fn — the concurrent-Execute deadlock
    contract) while the worker absorbs the injected delay fault;
    overlap accounting reads hidden when taken late, exposed when taken
    immediately; resubmit drops the poisoned lineage; close() joins the
    worker (mocolint JX011 contract)."""
    import threading as _threading
    import time as _time

    from moco_tpu.utils import faults

    dispatch_threads = []

    def gather(state):
        dispatch_threads.append(_threading.get_ident())
        return state * 2

    faults.install(f"delay@site={AsyncParamGather.FAULT_SITE}:seconds=0.05")
    try:
        g = AsyncParamGather(gather)
        g.submit(1)
        _time.sleep(0.15)  # "compute" hides the whole (delayed) gather
        assert g.take() == 2
        assert g.last_overlap is not None and g.last_overlap > 0.5
        g.submit(2)
        assert g.take() == 4  # immediate take: the delay is fully exposed
        assert g.last_overlap < 0.5
        # every dispatch ran on THIS thread, never the worker
        assert set(dispatch_threads) == {_threading.get_ident()}
        # rollback path: drop the in-flight gather, adopt the clean state
        g.submit(3)
        g.resubmit(10)
        assert g.take() == 20
    finally:
        faults.clear()

    # a post-hand-off ripen failure is an async-value error: take()
    # still returns the value (it surfaces at the consumer, as jax
    # async errors always do) and the worker SURVIVES to serve more
    class Boom:
        def block_until_ready(self):
            raise RuntimeError("boom")

    g2 = AsyncParamGather(lambda s: Boom() if s == "bad" else s)
    g2.submit("bad")
    assert isinstance(g2.take(), Boom)
    g2.submit("fine")
    assert g2.take() == "fine"
    # without any absorbed stall there is nothing to report
    assert g2.last_overlap is None
    for worker in (g, g2):
        worker.close()
        assert not worker._thread.is_alive()
    with pytest.raises(RuntimeError, match="closed"):
        g.submit(4)


@pytest.mark.slow  # full step + probe-surgery chain
def test_zero_checkpoint_restores_into_lincls(tmp_path):
    """A ZeRO-trained checkpoint must restore through the downstream
    template builders: the driver records the train-time mesh width in
    extras, and load_pretrained_backbone rebuilds the (num_data, m)
    opt-state layout from it (regression: it used to build a replicated
    template and fail the StandardRestore shape match)."""
    from moco_tpu.data.datasets import SyntheticDataset
    from moco_tpu.lincls import load_pretrained_backbone
    from moco_tpu.train import train

    config = _config(zero=True, optimizer="adamw")
    config = dataclasses.replace(
        config,
        optim=dataclasses.replace(config.optim, epochs=1),
        workdir=str(tmp_path / "pre_zero"),
        log_every=100,
    )
    dataset = SyntheticDataset(num_examples=2 * BATCH, image_size=IMG)
    train(config, dataset=dataset)

    # config=None: arch/optimizer/ZeRO layout all come from the checkpoint
    params, stats, cfg = load_pretrained_backbone(config.workdir)
    assert cfg.parallel.shard_weight_update
    assert jax.tree.leaves(params)


@pytest.mark.slow  # three driver runs (zero1 -> zero23 -> replicated resumes)
def test_zero_resume_resharded_roundtrip(tmp_path):
    """The 'compatible but resharded' resume, end to end: a zero1
    checkpoint resumes at stage 2/3 (restore into the checkpoint's own
    layout, host reshard), the stage-2/3 checkpoint resumes replicated,
    and the final stage-2/3 checkpoint loads through the eval-path
    gather in load_pretrained_backbone."""
    from moco_tpu.data.datasets import SyntheticDataset
    from moco_tpu.lincls import load_pretrained_backbone
    from moco_tpu.train import train

    base = _config(zero=True, optimizer="adamw", stage=1)
    wd = str(tmp_path / "pre_reshard")
    cfg1 = dataclasses.replace(
        base,
        optim=dataclasses.replace(base.optim, epochs=1),
        workdir=wd,
        log_every=100,
    )
    ds = SyntheticDataset(num_examples=2 * BATCH, image_size=IMG)
    train(cfg1, dataset=ds)

    # zero1 -> zero23: resume the same workdir one epoch further
    cfg2 = dataclasses.replace(
        cfg1,
        optim=dataclasses.replace(cfg1.optim, epochs=2),
        parallel=dataclasses.replace(cfg1.parallel, zero_stage=3),
    )
    train(cfg2, dataset=ds)

    # the stage-2/3 checkpoint serves the probe loader via the one-shot
    # eval gather (the layout is discovered from the checkpoint config)
    params, stats, cfg = load_pretrained_backbone(wd)
    assert cfg.parallel.zero_stage >= 2
    leaves = jax.tree.leaves(params)
    assert leaves and all(np.asarray(l).ndim >= 1 for l in leaves)

    # zero23 -> replicated: the downshard direction of the same machinery
    cfg3 = dataclasses.replace(
        cfg2,
        optim=dataclasses.replace(cfg2.optim, epochs=3),
        parallel=dataclasses.replace(
            cfg2.parallel, shard_weight_update=False, zero_stage=1
        ),
    )
    result = train(cfg3, dataset=ds)
    assert result["epoch"] == 2
