"""End-to-end driver smoke: train() on synthetic data, resume, CLI config.

The reference has no tests (SURVEY.md §4); its implicit e2e check is
"loss goes down and checkpoints restore". Reproduced here in miniature.
"""

import dataclasses
import json
import os

import numpy as np
import pytest

from moco_tpu.data.datasets import SyntheticDataset
from moco_tpu.utils.config import DataConfig, MocoConfig, OptimConfig, ParallelConfig, TrainConfig


def _tiny_config(workdir, epochs=2, shuffle="gather_perm"):
    return TrainConfig(
        moco=MocoConfig(
            arch="resnet18",
            dim=16,
            num_negatives=64,
            temperature=0.2,
            mlp=True,
            shuffle=shuffle,
            cifar_stem=True,
            compute_dtype="float32",
        ),
        optim=OptimConfig(lr=0.03, epochs=epochs, cos=True),
        data=DataConfig(dataset="synthetic", image_size=16, global_batch=16, num_workers=2),
        parallel=ParallelConfig(),
        workdir=str(workdir),
        log_every=2,
    )


@pytest.fixture(scope="module")
def trained(tmp_path_factory):
    from moco_tpu.train import train

    workdir = tmp_path_factory.mktemp("train_e2e")
    config = _tiny_config(workdir)
    dataset = SyntheticDataset(num_examples=64, image_size=16)
    result = train(config, dataset=dataset)
    return config, dataset, result


def test_train_runs_and_reports(trained):
    _, _, result = trained
    assert result["epoch"] == 1
    assert np.isfinite(result["loss"])
    assert 0.0 <= result["acc1"] <= 100.0


def test_train_writes_metrics_and_checkpoints(trained):
    config, _, _ = trained
    lines = [json.loads(l) for l in open(os.path.join(config.workdir, "metrics.jsonl"))]
    assert lines and {"loss", "acc1", "lr", "epoch"} <= set(lines[-1])
    # lr followed the cosine schedule downward across epochs
    lrs = [l["lr"] for l in lines]
    assert lrs[-1] < lrs[0]


def test_train_resumes_from_checkpoint(trained):
    from moco_tpu.train import train

    config, dataset, _ = trained
    # extend epochs; train() must resume at epoch 2, not restart
    config3 = dataclasses.replace(config, optim=dataclasses.replace(config.optim, epochs=3))
    result = train(config3, dataset=dataset)
    assert result["epoch"] == 2


def test_sigterm_checkpoints_and_exits_cleanly(tmp_path):
    """Preemption: SIGTERM mid-training -> save within a step, clean
    return, resumable state; original handlers restored afterwards."""
    import os
    import signal
    import threading

    from moco_tpu.train import train
    from moco_tpu.utils.checkpoint import CheckpointManager

    config = _tiny_config(tmp_path / "preempt", epochs=50, shuffle="none")
    dataset = SyntheticDataset(num_examples=64, image_size=16)
    before_handler = signal.getsignal(signal.SIGTERM)
    timer = threading.Timer(6.0, lambda: os.kill(os.getpid(), signal.SIGTERM))
    timer.start()
    try:
        train(config, dataset=dataset)  # returns early instead of dying
    finally:
        timer.cancel()
    assert signal.getsignal(signal.SIGTERM) is before_handler
    mgr = CheckpointManager(str(config.workdir))
    assert mgr.latest_step() is not None
    extra = mgr.read_extra()
    assert extra["epoch"] < 49  # exited before finishing all 50 epochs
    mgr.close()


def test_cli_maps_reference_flags(tmp_path):
    import train as cli

    args = cli.build_parser().parse_args(
        [
            "--arch", "resnet50", "--mlp", "--aug-plus", "--cos",
            "--moco-t", "0.2", "--lr", "0.03", "--batch-size", "256",
            "--epochs", "200", "--workdir", str(tmp_path),
        ]
    )
    cfg = cli.config_from_args(args)
    assert cfg.moco.arch == "resnet50" and cfg.moco.mlp
    assert cfg.moco.temperature == 0.2
    assert cfg.optim.cos and cfg.optim.lr == 0.03
    assert cfg.data.global_batch == 256 and cfg.data.aug_plus
    assert cfg.workdir == str(tmp_path)


def test_cli_preset_with_override(tmp_path):
    import train as cli

    args = cli.build_parser().parse_args(
        ["--preset", "cifar_smoke", "--epochs", "1", "--workdir", str(tmp_path)]
    )
    cfg = cli.config_from_args(args)
    assert cfg.moco.arch == "resnet18" and cfg.moco.cifar_stem
    assert cfg.optim.epochs == 1  # override wins over preset
