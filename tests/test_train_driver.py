"""End-to-end driver smoke: train() on synthetic data, resume, CLI config.

The reference has no tests (SURVEY.md §4); its implicit e2e check is
"loss goes down and checkpoints restore". Reproduced here in miniature.
"""

import dataclasses
import json
import os

import numpy as np
import pytest

from moco_tpu.data.datasets import SyntheticDataset
from moco_tpu.utils.config import DataConfig, MocoConfig, OptimConfig, ParallelConfig, TrainConfig


def _tiny_config(workdir, epochs=2, shuffle="gather_perm"):
    return TrainConfig(
        moco=MocoConfig(
            arch="resnet18",
            dim=16,
            num_negatives=64,
            temperature=0.2,
            mlp=True,
            shuffle=shuffle,
            cifar_stem=True,
            compute_dtype="float32",
        ),
        optim=OptimConfig(lr=0.03, epochs=epochs, cos=True),
        data=DataConfig(dataset="synthetic", image_size=16, global_batch=16, num_workers=2),
        parallel=ParallelConfig(),
        workdir=str(workdir),
        log_every=2,
    )


@pytest.fixture(scope="module")
def trained(tmp_path_factory):
    from moco_tpu.train import train

    workdir = tmp_path_factory.mktemp("train_e2e")
    config = _tiny_config(workdir)
    dataset = SyntheticDataset(num_examples=64, image_size=16)
    result = train(config, dataset=dataset)
    return config, dataset, result


# The full-driver e2e tests compile and run real training loops over the
# 8-virtual-device mesh — minutes each on a CPU host. They carry the
# `slow` marker (tier-1 deselects them); CI's chaos-smoke job exercises
# the same driver paths end-to-end in every PR.
@pytest.mark.slow
def test_train_runs_and_reports(trained):
    _, _, result = trained
    assert result["epoch"] == 1
    assert np.isfinite(result["loss"])
    assert 0.0 <= result["acc1"] <= 100.0


@pytest.mark.slow
def test_train_writes_metrics_and_checkpoints(trained):
    config, _, _ = trained
    lines = [json.loads(l) for l in open(os.path.join(config.workdir, "metrics.jsonl"))]
    assert lines and {"loss", "acc1", "lr", "epoch"} <= set(lines[-1])
    # lr followed the cosine schedule downward across epochs
    lrs = [l["lr"] for l in lines]
    assert lrs[-1] < lrs[0]


@pytest.mark.slow
def test_train_resumes_from_checkpoint(trained):
    from moco_tpu.train import train

    config, dataset, _ = trained
    # extend epochs; train() must resume at epoch 2, not restart
    config3 = dataclasses.replace(config, optim=dataclasses.replace(config.optim, epochs=3))
    result = train(config3, dataset=dataset)
    assert result["epoch"] == 2


@pytest.mark.slow
def test_sigterm_checkpoints_and_exits_cleanly(tmp_path):
    """Preemption: SIGTERM mid-training -> save within a step, clean
    return, resumable state; original handlers restored afterwards."""
    import os
    import signal
    import threading

    from moco_tpu.train import train
    from moco_tpu.utils.checkpoint import CheckpointManager

    config = _tiny_config(tmp_path / "preempt", epochs=50, shuffle="none")
    dataset = SyntheticDataset(num_examples=64, image_size=16)
    before_handler = signal.getsignal(signal.SIGTERM)
    timer = threading.Timer(6.0, lambda: os.kill(os.getpid(), signal.SIGTERM))
    timer.start()
    try:
        train(config, dataset=dataset)  # returns early instead of dying
    finally:
        timer.cancel()
    assert signal.getsignal(signal.SIGTERM) is before_handler
    mgr = CheckpointManager(str(config.workdir))
    assert mgr.latest_step() is not None
    extra = mgr.read_extra()
    assert extra["epoch"] < 49  # exited before finishing all 50 epochs
    mgr.close()


@pytest.mark.slow
def test_preempt_fault_resume_and_nan_guard(tmp_path):
    """Injected-fault end-to-end (fault-tolerance layer):

    1. deterministic SIGTERM mid-epoch (preempt fault at global step 3 of
       a 3-epoch / 2-steps-per-epoch run) -> mid-epoch checkpoint, clean
       early return, at most one step of overrun;
    2. resume redoes the partial epoch at its full step count and — with
       a NaN loss injected at one resumed step — the non-finite guard
       skips that update while keeping the step counter advancing, so the
       run still completes at exactly the fault-free total.
    """
    import json

    from moco_tpu.train import train
    from moco_tpu.utils import faults
    from moco_tpu.utils.checkpoint import CheckpointManager

    spe = 2  # 32 examples / batch 16
    config = dataclasses.replace(
        _tiny_config(tmp_path / "chaos", epochs=3, shuffle="none"), log_every=1
    )
    dataset = SyntheticDataset(num_examples=32, image_size=16)

    faults.install("preempt@step=3")
    try:
        train(config, dataset=dataset)
    finally:
        faults.clear()
    mgr = CheckpointManager(str(config.workdir))
    mid_step = mgr.latest_step()
    mid_extra = mgr.read_extra()
    mgr.close()
    # SIGTERM landed at step 3 (epoch 1's first step); the save happens
    # within one step and records epoch 0 as the last COMPLETED epoch
    assert mid_extra["epoch"] == 0
    assert spe < mid_step <= 2 * spe  # mid-epoch, at most one step late

    faults.install("nan@step=5")  # one resumed step observes NaN loss
    try:
        result = train(config, dataset=dataset)
    finally:
        faults.clear()
    assert result["epoch"] == 2  # ran to completion
    mgr = CheckpointManager(str(config.workdir))
    final_step = mgr.latest_step()
    mgr.close()
    # the redone partial epoch has its full step count: final id is the
    # preemption save plus exactly the 2 redone epochs
    assert final_step == mid_step + 2 * spe
    # ...and the preemption cost at most one checkpoint interval of work
    assert final_step - 3 * spe <= spe
    events = [
        json.loads(l)
        for l in open(os.path.join(config.workdir, "metrics.jsonl"))
    ]
    nan_events = [e for e in events if e.get("event") == "nonfinite_loss"]
    assert len(nan_events) == 1 and nan_events[0]["nan_steps"] == 1


@pytest.mark.slow
def test_nan_guard_aborts_past_threshold(tmp_path):
    """Persistent divergence must kill the run with diagnostics, not
    burn the fleet: every log step NaN + threshold 2 -> abort on the
    second event."""
    from moco_tpu.train import train
    from moco_tpu.utils import faults

    config = dataclasses.replace(
        _tiny_config(tmp_path / "nan_abort", epochs=2, shuffle="none"),
        log_every=1,
        nan_guard_threshold=2,
    )
    dataset = SyntheticDataset(num_examples=32, image_size=16)
    faults.install("nan@step=1:times=99")
    try:
        with pytest.raises(FloatingPointError, match="non-finite"):
            train(config, dataset=dataset)
    finally:
        faults.clear()


@pytest.mark.slow
def test_resume_incompatible_config_fails_fast(trained):
    """Resuming under a structurally different config raises the
    field-by-field diff BEFORE restoring (a shape-mismatch restore would
    read as corruption and quarantine a good checkpoint)."""
    from moco_tpu.train import train
    from moco_tpu.utils.config import ResumeCompatError

    config, dataset, _ = trained
    bad = dataclasses.replace(
        config,
        moco=dataclasses.replace(config.moco, dim=32),
        optim=dataclasses.replace(config.optim, epochs=5),
    )
    with pytest.raises(ResumeCompatError, match="moco.dim"):
        train(bad, dataset=dataset)
    # nothing was quarantined for it
    assert not os.path.isdir(os.path.join(config.workdir, "quarantine"))


def test_cli_maps_reference_flags(tmp_path):
    import train as cli

    args = cli.build_parser().parse_args(
        [
            "--arch", "resnet50", "--mlp", "--aug-plus", "--cos",
            "--moco-t", "0.2", "--lr", "0.03", "--batch-size", "256",
            "--epochs", "200", "--workdir", str(tmp_path),
            "--watchdog-timeout", "300", "--nan-guard-threshold", "5",
        ]
    )
    cfg = cli.config_from_args(args)
    assert cfg.moco.arch == "resnet50" and cfg.moco.mlp
    assert cfg.moco.temperature == 0.2
    assert cfg.optim.cos and cfg.optim.lr == 0.03
    assert cfg.data.global_batch == 256 and cfg.data.aug_plus
    assert cfg.workdir == str(tmp_path)
    assert cfg.watchdog_timeout == 300.0 and cfg.nan_guard_threshold == 5


def test_cli_preset_with_override(tmp_path):
    import train as cli

    args = cli.build_parser().parse_args(
        ["--preset", "cifar_smoke", "--epochs", "1", "--workdir", str(tmp_path)]
    )
    cfg = cli.config_from_args(args)
    assert cfg.moco.arch == "resnet18" and cfg.moco.cifar_stem
    assert cfg.optim.epochs == 1  # override wins over preset
