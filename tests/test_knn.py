"""kNN monitor: classifier correctness on separable data + e2e extract."""

import jax
import jax.numpy as jnp
import numpy as np

from moco_tpu.knn import extract_features, knn_classify, knn_eval
from moco_tpu.models import create_resnet
from moco_tpu.ops.losses import l2_normalize


def test_knn_classifier_on_separable_clusters():
    rng = np.random.default_rng(0)
    centers = np.eye(4, 16, dtype=np.float32) * 5
    train = np.concatenate([centers[i] + rng.normal(0, 0.1, (50, 16)) for i in range(4)])
    train_y = np.repeat(np.arange(4), 50)
    test = np.concatenate([centers[i] + rng.normal(0, 0.1, (10, 16)) for i in range(4)])
    test_y = np.repeat(np.arange(4), 10)
    train = np.asarray(l2_normalize(jnp.asarray(train)))
    test = np.asarray(l2_normalize(jnp.asarray(test)))
    preds = knn_classify(train, train_y, test, num_classes=4, k=20)
    assert (preds == test_y).mean() == 1.0


def test_knn_eval_end_to_end_synthetic():
    from moco_tpu.data.datasets import SyntheticDataset

    backbone = create_resnet("resnet18", cifar_stem=True)
    x = jnp.zeros((1, 16, 16, 3))
    variables = backbone.init(jax.random.PRNGKey(0), x, train=False)
    train_ds = SyntheticDataset(num_examples=32, image_size=16, num_classes=4)
    test_ds = SyntheticDataset(num_examples=16, image_size=16, num_classes=4)
    acc = knn_eval(
        backbone,
        variables["params"],
        variables.get("batch_stats", {}),
        train_ds,
        test_ds,
        num_classes=4,
        k=8,
        batch_size=16,
        image_size=16,
    )
    assert 0.0 <= acc <= 100.0


def test_extract_features_normalized():
    from moco_tpu.data.datasets import SyntheticDataset

    backbone = create_resnet("resnet18", cifar_stem=True)
    variables = backbone.init(jax.random.PRNGKey(0), jnp.zeros((1, 16, 16, 3)), train=False)
    ds = SyntheticDataset(num_examples=8, image_size=16)
    feats, labels = extract_features(
        backbone, variables["params"], variables.get("batch_stats", {}), ds,
        batch_size=4, image_size=16,
    )
    assert feats.shape == (8, backbone.num_features)
    np.testing.assert_allclose(np.linalg.norm(feats, axis=1), 1.0, rtol=1e-5)


def test_extract_features_sharded_matches_single_device():
    """mesh-parallel extraction == single-device extraction."""
    import jax

    from moco_tpu.core import build_encoder
    from moco_tpu.data.datasets import LearnableSyntheticDataset
    from moco_tpu.knn import extract_features
    from moco_tpu.parallel import create_mesh
    from moco_tpu.utils.config import MocoConfig

    cfg = MocoConfig(arch="resnet18", dim=32, cifar_stem=True, compute_dtype="float32", shuffle="none")
    encoder = build_encoder(cfg)
    ds = LearnableSyntheticDataset(40, 16, 4)  # 40 % 16 != 0: ragged tail
    import jax.numpy as jnp

    v = encoder.backbone.init(jax.random.PRNGKey(0), jnp.zeros((1, 16, 16, 3)), train=False)
    mesh = create_mesh()
    f1, y1 = extract_features(
        encoder.backbone, v["params"], v.get("batch_stats", {}), ds, batch_size=16, image_size=16
    )
    f2, y2 = extract_features(
        encoder.backbone, v["params"], v.get("batch_stats", {}), ds,
        batch_size=16, image_size=16, mesh=mesh,
    )
    np.testing.assert_array_equal(y1, y2)
    np.testing.assert_allclose(f1, f2, rtol=2e-5, atol=2e-5)
