"""Perf ledger + regression gate (scripts/perf_ledger.py): append
builds entries from bench JSON (raw line or BENCH_r*.json wrapper),
check gates on the last comparable metric with platform-aware
thresholds, and the tracked PERF_LEDGER.json seed stays loadable."""

import json
import os

import pytest

from tests.conftest import load_script

ledger_mod = load_script("perf_ledger.py")


def _write(path, obj):
    with open(path, "w") as f:
        json.dump(obj, f)
    return str(path)


BENCH_TPU = {
    "metric": "moco_v2_r50_pretrain_imgs_per_sec_per_chip",
    "value": 2000.0,
    "unit": "imgs/sec/chip",
    "mfu": 0.31,
    "overlap_efficiency": 0.95,
    "legs": {"accelerator": {"ran": True, "skip_reason": None}},
}


def test_append_and_check_pass(tmp_path):
    ledger = str(tmp_path / "ledger.json")
    bench = _write(tmp_path / "b1.json", BENCH_TPU)
    entry = ledger_mod.append(ledger, bench, "r10", note="unit")
    assert entry["platform"] == "tpu" and entry["value"] == 2000.0
    # within 10%: pass
    cand = _write(tmp_path / "b2.json", {**BENCH_TPU, "value": 1850.0})
    assert ledger_mod.check(ledger, cand) == 0


def test_check_fails_on_regression(tmp_path):
    ledger = str(tmp_path / "ledger.json")
    ledger_mod.append(ledger, _write(tmp_path / "b1.json", BENCH_TPU), "r10")
    cand = _write(tmp_path / "b2.json", {**BENCH_TPU, "value": 1700.0})  # -15%
    assert ledger_mod.check(ledger, cand) == 1
    # explicit looser threshold overrides the default
    assert ledger_mod.check(ledger, cand, threshold=0.2) == 0


def test_check_cpu_smoke_uses_wide_threshold(tmp_path):
    ledger = str(tmp_path / "ledger.json")
    cpu = {"metric": "moco_v1_r18_cpu_smoke_imgs_per_sec", "value": 10.0}
    ledger_mod.append(ledger, _write(tmp_path / "c1.json", cpu), "r10")
    # -40% on a shared CI runner: inside the 50% CPU noise floor
    assert ledger_mod.check(ledger, _write(tmp_path / "c2.json", {**cpu, "value": 6.0})) == 0
    # -60%: catastrophic, still gated
    assert ledger_mod.check(ledger, _write(tmp_path / "c3.json", {**cpu, "value": 3.9})) == 1


def test_check_without_comparable_entry_passes(tmp_path):
    ledger = str(tmp_path / "ledger.json")
    ledger_mod.append(ledger, _write(tmp_path / "b1.json", BENCH_TPU), "r10")
    other = {"metric": "moco_v3_vit_b16_pretrain_imgs_per_sec_per_chip", "value": 1.0}
    assert ledger_mod.check(ledger, _write(tmp_path / "o.json", other)) == 0
    # an empty/missing ledger also passes (gate needs a comparable leg)
    assert ledger_mod.check(str(tmp_path / "none.json"), _write(tmp_path / "o2.json", other)) == 0


def test_append_reads_bench_wrapper_format(tmp_path):
    ledger = str(tmp_path / "ledger.json")
    wrapper = {"n": 1, "rc": 0, "parsed": {**BENCH_TPU, "value": 1234.0}}
    entry = ledger_mod.append(ledger, _write(tmp_path / "w.json", wrapper), "r11")
    assert entry["value"] == 1234.0
    data = json.load(open(ledger))
    assert data["entries"][-1]["run_id"] == "r11"


def test_tracked_seed_ledger_is_valid():
    path = os.path.join(os.path.dirname(__file__), "..", "PERF_LEDGER.json")
    ledger = ledger_mod.load_ledger(path)
    assert len(ledger["entries"]) >= 5
    metrics = {e["metric"] for e in ledger["entries"]}
    assert "moco_v2_r50_pretrain_imgs_per_sec_per_chip" in metrics
    # every entry carries the fields the gate needs
    for e in ledger["entries"]:
        assert "run_id" in e and "metric" in e and "platform" in e


def test_value_none_is_not_gated(tmp_path):
    ledger = str(tmp_path / "ledger.json")
    ledger_mod.append(ledger, _write(tmp_path / "b1.json", BENCH_TPU), "r10")
    cand = {"metric": BENCH_TPU["metric"], "value": None}
    assert ledger_mod.check(ledger, _write(tmp_path / "n.json", cand)) == 0


# -- ISSUE 11 tier gates: fused IVF + quantized engine --------------------

ANN_CPU = {
    "metric": "moco_v1_r18_cpu_smoke_imgs_per_sec",
    "value": 10.0,
    "ann_ab": {
        "metric": "moco_ann_ivf_cpu_smoke_queries_per_sec",
        "value": 300.0,
        "recall_at_10": 1.0,
        "fused": {"qps": 900.0, "recall_at_10": 1.0},
    },
}


def test_fused_tier_gates(tmp_path):
    ledger = str(tmp_path / "ledger.json")
    # fused beats composed at full recall: pass
    assert ledger_mod.check(ledger, _write(tmp_path / "a1.json", ANN_CPU)) == 0
    # fused recall below the floor: fail (recall-gated like every tier)
    bad = json.loads(json.dumps(ANN_CPU))
    bad["ann_ab"]["fused"]["recall_at_10"] = 0.90
    assert ledger_mod.check(ledger, _write(tmp_path / "a2.json", bad)) == 1
    # fused slower than 0.75x composed on the cpu smoke: fail
    slow = json.loads(json.dumps(ANN_CPU))
    slow["ann_ab"]["fused"]["qps"] = 200.0
    assert ledger_mod.check(ledger, _write(tmp_path / "a3.json", slow)) == 1
    # on an accelerator metric the ratio floor is a hard 1.0
    accel = json.loads(json.dumps(ANN_CPU))
    accel["ann_ab"]["metric"] = "moco_ann_ivf_queries_per_sec"
    accel["ann_ab"]["fused"]["qps"] = 290.0  # 0.97x composed
    assert ledger_mod.check(ledger, _write(tmp_path / "a4.json", accel)) == 1


SERVE_QUANT_CPU = {
    "metric": "moco_v1_r18_cpu_smoke_imgs_per_sec",
    "value": 10.0,
    "serving": {
        "metric": "moco_serve_resnet18_cpu_smoke_queries_per_sec",
        "value": 8.0,
        "quant": {
            "w8": {"qps": 7.5, "cosine_vs_f32": 0.9999},
            "w8a8": {"qps": 7.4, "cosine_vs_f32": 0.9995, "int8_kernels": False},
        },
    },
}


def test_quant_tier_gates(tmp_path):
    ledger = str(tmp_path / "ledger.json")
    # cosine floors held, w8a8 within the cpu ratio slack: pass
    assert ledger_mod.check(ledger, _write(tmp_path / "q1.json", SERVE_QUANT_CPU)) == 0
    # cosine below the 0.99 floor: fail on ANY platform
    bad = json.loads(json.dumps(SERVE_QUANT_CPU))
    bad["serving"]["quant"]["w8a8"]["cosine_vs_f32"] = 0.97
    assert ledger_mod.check(ledger, _write(tmp_path / "q2.json", bad)) == 1
    # catastrophic w8a8 slowdown: fail even with the cpu slack
    slow = json.loads(json.dumps(SERVE_QUANT_CPU))
    slow["serving"]["quant"]["w8a8"]["qps"] = 4.0
    assert ledger_mod.check(ledger, _write(tmp_path / "q3.json", slow)) == 1
    # accelerator serving: w8a8 must actually beat w8
    accel = json.loads(json.dumps(SERVE_QUANT_CPU))
    accel["serving"]["metric"] = "moco_serve_resnet50_queries_per_sec_per_chip"
    accel["serving"]["quant"]["w8a8"]["qps"] = 7.0  # < w8
    assert ledger_mod.check(ledger, _write(tmp_path / "q4.json", accel)) == 1
    accel["serving"]["quant"]["w8a8"]["qps"] = 12.0  # beats w8
    assert ledger_mod.check(ledger, _write(tmp_path / "q5.json", accel)) == 0


# -- show: tracked-series summary survives skip-only rounds ----------------


def test_show_summarizes_series_despite_skip_only_tail(tmp_path, capsys):
    """Regression: a latest round whose legs all hit the skip ledger
    (value None across the board) must not make `show` read empty — the
    summary block reports the latest REAL point per tracked series."""
    ledger = str(tmp_path / "ledger.json")
    full = {
        "metric": "moco_v1_r18_cpu_smoke_imgs_per_sec",
        "value": 9.5,
        "unit": "imgs/sec/chip",
        "serving": {
            "metric": "moco_serve_resnet18_cpu_smoke_queries_per_sec",
            "value": 8.2,
            "unit": "queries/sec",
        },
        "ann_ab": {
            "metric": "moco_ann_ivf_cpu_smoke_queries_per_sec",
            "value": 310.0,
        },
        "legs": {"serving": {"ran": True, "skip_reason": None}},
    }
    skip_only = {
        "metric": "moco_v1_r18_cpu_smoke_imgs_per_sec",
        "value": None,
        "serving": {"metric": "moco_serve_resnet18_cpu_smoke_queries_per_sec", "value": None},
        "ann_ab": {"metric": "moco_ann_ivf_cpu_smoke_queries_per_sec", "value": None},
        "legs": {
            "accelerator": {"ran": False, "skip_reason": "pinned cpu"},
            "serving": {"ran": False, "skip_reason": "BENCH_SKIP_SERVE set"},
        },
    }
    ledger_mod.append(ledger, _write(tmp_path / "s1.json", full), "r20")
    ledger_mod.append(ledger, _write(tmp_path / "s2.json", skip_only), "r21")
    assert ledger_mod.show(ledger) == 0
    out = capsys.readouterr().out
    assert "(all legs skipped)" in out
    assert "tracked series (latest real point):" in out
    assert "moco_v1_r18_cpu_smoke_imgs_per_sec = 9.5 imgs/sec/chip  (run r20)" in out
    assert "moco_serve_resnet18_cpu_smoke_queries_per_sec = 8.2" in out
    assert "moco_ann_ivf_cpu_smoke_queries_per_sec = 310.0  (run r20)" in out


def test_show_on_tracked_seed_ledger(capsys):
    """The in-repo ledger itself: every series the repo has measured
    shows a latest real point (this is the 'trajectory reads empty'
    bug's acceptance check against real data)."""
    path = os.path.join(os.path.dirname(__file__), "..", "PERF_LEDGER.json")
    assert ledger_mod.show(path) == 0
    out = capsys.readouterr().out
    assert "tracked series (latest real point):" in out
    for series in (
        "moco_v1_r18_cpu_smoke_imgs_per_sec",
        "moco_v2_r50_pretrain_imgs_per_sec_per_chip",
        "moco_serve_resnet18_cpu_smoke_queries_per_sec",
        "moco_ann_ivf_cpu_smoke_queries_per_sec",
    ):
        assert f"{series} = " in out
