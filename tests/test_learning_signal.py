"""End-to-end learning signal at CI scale (VERDICT r1 item 2).

The reference's only QA mechanism is end-to-end metric reproduction
(SURVEY.md §4). This is its CI-sized equivalent: a short MoCo v2
pretrain on the class-structured `LearnableSyntheticDataset` must push
frozen-feature kNN top-1 well above chance. Runs on the 8-virtual-CPU
mesh like the rest of the suite — small model, few epochs.
"""

import jax
import numpy as np
import pytest

from moco_tpu.data.datasets import LearnableSyntheticDataset
from moco_tpu.knn import knn_eval
from moco_tpu.train import train
from moco_tpu.utils.config import (
    DataConfig,
    MocoConfig,
    OptimConfig,
    ParallelConfig,
    TrainConfig,
)

NUM_CLASSES = 8
CHANCE = 100.0 / NUM_CLASSES


@pytest.mark.slow
def test_pretrain_knn_beats_chance(tmp_path):
    n_dev = len(jax.devices())
    config = TrainConfig(
        moco=MocoConfig(
            arch="resnet18",
            dim=64,
            num_negatives=256,
            momentum=0.9,
            temperature=0.2,
            mlp=True,
            shuffle="gather_perm" if n_dev > 1 else "none",
            cifar_stem=True,
            compute_dtype="float32",
        ),
        optim=OptimConfig(lr=0.12, epochs=4, cos=True),
        data=DataConfig(
            dataset="synthetic_learnable", image_size=32, global_batch=64, aug_plus=True
        ),
        parallel=ParallelConfig(num_data=n_dev),
        workdir=str(tmp_path),
        knn_every_epochs=0,
        seed=0,
    )
    dataset = LearnableSyntheticDataset(512, 32, NUM_CLASSES, train=True)
    final = train(config, dataset=dataset)
    assert np.isfinite(final["loss"])

    # frozen-feature kNN on held-out instances of the same classes
    from moco_tpu.core import build_encoder
    from moco_tpu.utils.checkpoint import CheckpointManager
    from moco_tpu.core.moco import create_state
    from moco_tpu.utils.schedules import build_optimizer
    import jax.numpy as jnp

    encoder = build_encoder(config.moco, num_data=n_dev)
    tx = build_optimizer(config.optim, steps_per_epoch=8)
    sample = jnp.zeros((1, 32, 32, 3), jnp.float32)
    state = create_state(jax.random.PRNGKey(0), config, encoder, tx, sample)
    ckpt = CheckpointManager(str(tmp_path), keep=3)
    state, _ = ckpt.restore(state)
    ckpt.close()

    bank = LearnableSyntheticDataset(512, 32, NUM_CLASSES, train=True)
    test = LearnableSyntheticDataset(128, 32, NUM_CLASSES, train=False)
    top1 = knn_eval(
        encoder.backbone,
        state.params_q["backbone"],
        state.batch_stats_q.get("backbone", {}),
        bank,
        test,
        num_classes=NUM_CLASSES,
        k=32,
        image_size=32,
    )
    # chance is 12.5%; a learning encoder lands far above it even at
    # this CI scale (typically >50%) — the margin guards against flaky
    # near-chance passes without requiring a long run
    assert top1 > 2.0 * CHANCE, f"kNN top-1 {top1:.1f}% not above 2x chance"
