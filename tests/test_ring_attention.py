"""Ring attention == dense attention over the gathered sequence.

Runs under shard_map on the 8-virtual-CPU-device mesh (conftest), the
same harness the other cross-replica patterns use (SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from moco_tpu.ops.flash_attention import _attn_reference
from moco_tpu.parallel.ring_attention import ring_attention
from moco_tpu.parallel.compat import shard_map

B, H, D = 2, 2, 32
SEQ_AXIS = "seq"


def _mesh(n):
    return Mesh(np.array(jax.devices()[:n]), (SEQ_AXIS,))


@pytest.mark.parametrize("n_dev,s_local", [(4, 64), (8, 32), (2, 128)])
def test_matches_dense_full_sequence(n_dev, s_local):
    mesh = _mesh(n_dev)
    s_total = n_dev * s_local
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q, k, v = (jax.random.normal(kk, (B, H, s_total, D), jnp.float32) for kk in ks)

    ring = jax.jit(
        shard_map(
            lambda q, k, v: ring_attention(q, k, v, SEQ_AXIS, block_q=32, block_k=32, interpret=True),
            mesh=mesh,
            in_specs=(P(None, None, SEQ_AXIS), P(None, None, SEQ_AXIS), P(None, None, SEQ_AXIS)),
            out_specs=P(None, None, SEQ_AXIS),
            check_vma=False,
        )
    )
    out = ring(q, k, v)
    ref, _ = _attn_reference(q, k, v, D**-0.5)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4)


def test_differentiable_through_ring():
    n_dev, s_local = 4, 32
    mesh = _mesh(n_dev)
    s_total = n_dev * s_local
    ks = jax.random.split(jax.random.PRNGKey(1), 3)
    q, k, v = (jax.random.normal(kk, (B, H, s_total, D), jnp.float32) for kk in ks)

    def ring_loss(q, k, v):
        f = shard_map(
            lambda q, k, v: ring_attention(q, k, v, SEQ_AXIS, block_q=32, block_k=32, interpret=True),
            mesh=mesh,
            in_specs=(P(None, None, SEQ_AXIS),) * 3,
            out_specs=P(None, None, SEQ_AXIS),
            check_vma=False,
        )
        return jnp.sum(f(q, k, v) ** 2)

    def dense_loss(q, k, v):
        return jnp.sum(_attn_reference(q, k, v, D**-0.5)[0] ** 2)

    g_ring = jax.jit(jax.grad(ring_loss, argnums=(0, 1, 2)))(q, k, v)
    g_dense = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
    for gr, gd in zip(g_ring, g_dense):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gd), rtol=1e-3, atol=1e-3)
