"""Decode-once packed RGB cache (moco_tpu/data/cache.py): cached reads
must be pixel-identical to the direct JPEG path — same `load` canvas,
same host-crop protocol output, same dims/labels — since the cache
stores the exact decoded full-geometry RGB. The point of the cache is
removing per-epoch codec work on few-core TPU hosts (the reference
leans on 32 DataLoader workers instead, `main_moco.py:~L256`)."""

import os

import numpy as np
import pytest
from PIL import Image

from moco_tpu.data.cache import PackedRGBCacheDataset, build_rgb_cache
from moco_tpu.data.datasets import ImageFolderDataset, build_dataset, sample_rrc_boxes


@pytest.fixture(scope="module")
def jpeg_folder(tmp_path_factory):
    root = tmp_path_factory.mktemp("imgs")
    rng = np.random.default_rng(0)
    # ragged geometries on purpose: wide, tall, tiny
    shapes = [(48, 64), (64, 48), (40, 40), (80, 56), (56, 80), (36, 52)]
    for c in range(2):
        (root / f"class_{c}").mkdir()
        for i, (h, w) in enumerate(shapes):
            arr = rng.integers(0, 256, (h, w, 3), dtype=np.uint8)
            Image.fromarray(arr).save(root / f"class_{c}" / f"im_{i}.jpg", quality=92)
    return str(root)


@pytest.fixture(scope="module")
def both(jpeg_folder, tmp_path_factory):
    # use_native=False: these tests assert BIT-exact equality with the
    # PIL path; the native resampler has its own tolerance-based parity
    # test below
    src = ImageFolderDataset(jpeg_folder, decode_size=32)
    cache_dir = str(tmp_path_factory.mktemp("cache"))
    build_rgb_cache(src, cache_dir, num_workers=2, canvas_size=32)
    return src, PackedRGBCacheDataset(cache_dir, decode_size=32, use_native=False)


def test_index_matches_source(both):
    src, cached = both
    assert len(cached) == len(src)
    assert cached.num_classes == src.num_classes
    idx = np.arange(len(src))
    np.testing.assert_array_equal(cached.dims(idx), src.dims(idx))
    for i in idx:
        assert int(cached.labels[i]) == src.samples[i][1]


def test_load_canvas_identical(both):
    # the fixture's canvas file matches decode_size, so this exercises
    # the zero-resize mmap row read
    src, cached = both
    assert cached._canvases is not None
    for i in range(len(src)):
        a, la = src.load(i)
        b, lb = cached.load(i)
        np.testing.assert_array_equal(a, b)
        assert la == lb


def test_load_canvas_fallback_resize(both):
    # a decode_size with no canvas file falls back to resizing the
    # cached full-geometry pixels — still identical to the JPEG path
    src, cached = both
    for i in range(0, len(src), 3):
        a, _ = src.load(i, decode_size=24)
        b, _ = cached.load(i, decode_size=24)
        np.testing.assert_array_equal(a, b)


def test_crop_batch_identical(both):
    src, cached = both
    idx = np.arange(len(src))
    rng = np.random.default_rng(7)
    dims = src.dims(idx)
    boxes = np.stack(
        [sample_rrc_boxes(rng, dims, scale=(0.2, 1.0)) for _ in range(2)], axis=1
    )
    a_imgs, a_lab = src.load_crop_batch(idx, boxes, out_size=24)
    b_imgs, b_lab = cached.load_crop_batch(idx, boxes, out_size=24)
    np.testing.assert_array_equal(a_imgs, b_imgs)
    np.testing.assert_array_equal(a_lab, b_lab)


def test_build_is_idempotent(both, tmp_path):
    src, cached = both
    # a second build over an existing complete cache is a no-op
    marker = os.path.getmtime
    cache_dir = os.path.dirname(cached._data.filename)
    t0 = marker(os.path.join(cache_dir, "data.bin"))
    build_rgb_cache(src, cache_dir)
    assert marker(os.path.join(cache_dir, "data.bin")) == t0


def test_build_dataset_wires_cache(jpeg_folder, tmp_path):
    ds = build_dataset(
        "imagefolder", jpeg_folder, image_size=28, cache_dir=str(tmp_path / "c")
    )
    assert isinstance(ds, PackedRGBCacheDataset)
    img, label = ds.load(0)
    assert img.shape == (32, 32, 3)  # decode canvas = round(28 * 256/224)


def test_stale_cache_from_other_source_raises(jpeg_folder, tmp_path):
    """A cache built from one root must refuse reuse against another
    (regression: it used to silently serve the wrong pixels/labels)."""
    cache_dir = str(tmp_path / "c")
    src = ImageFolderDataset(jpeg_folder, decode_size=32)
    build_rgb_cache(src, cache_dir, canvas_size=32, root=jpeg_folder)

    other = tmp_path / "other_root" / "class_0"
    other.mkdir(parents=True)
    Image.fromarray(np.zeros((40, 40, 3), np.uint8)).save(other / "im.jpg")
    with pytest.raises(ValueError, match="built from"):
        build_rgb_cache(
            lambda: ImageFolderDataset(str(tmp_path / "other_root"), decode_size=32),
            cache_dir,
            canvas_size=32,
            root=str(tmp_path / "other_root"),
        )


def test_complete_cache_tolerates_missing_source(jpeg_folder, tmp_path):
    """Reuse verifies the source fingerprint when the source is listable,
    but a since-removed data_dir must be tolerated — the cache is
    self-contained."""
    cache_dir = str(tmp_path / "c")
    build_rgb_cache(
        ImageFolderDataset(jpeg_folder, decode_size=32),
        cache_dir,
        canvas_size=32,
        root=jpeg_folder,
    )

    def gone():
        raise FileNotFoundError("data_dir was deleted after caching")

    build_rgb_cache(gone, cache_dir, canvas_size=32, root=jpeg_folder)
    assert len(PackedRGBCacheDataset(cache_dir, decode_size=32, use_native=False)) == 12


def test_changed_listing_under_same_root_raises(jpeg_folder, tmp_path):
    """Images added under the SAME root must invalidate the cache
    (fingerprint drift), not silently train on the stale subset."""
    import shutil

    root = str(tmp_path / "root_copy")
    shutil.copytree(jpeg_folder, root)
    cache_dir = str(tmp_path / "c")
    build_rgb_cache(
        ImageFolderDataset(root, decode_size=32), cache_dir, canvas_size=32, root=root
    )
    # grow the dataset in place
    Image.fromarray(np.zeros((40, 40, 3), np.uint8)).save(
        os.path.join(root, "class_0", "new_im.jpg")
    )
    with pytest.raises(ValueError, match="stale"):
        build_rgb_cache(
            lambda: ImageFolderDataset(root, decode_size=32),
            cache_dir,
            canvas_size=32,
            root=root,
        )


def test_new_canvas_size_grows_without_redecode(jpeg_folder, tmp_path):
    """Changing image_size against an existing cache must regrow the
    mmap canvas fast path rather than silently falling back to per-image
    resizes."""
    cache_dir = str(tmp_path / "c")
    src = ImageFolderDataset(jpeg_folder, decode_size=32)
    build_rgb_cache(src, cache_dir, canvas_size=32, root=jpeg_folder)
    # same cache, new size: canvas grows from the stored pixels — the
    # packed data file must not be rewritten (no re-decode)
    t0 = os.path.getmtime(os.path.join(cache_dir, "data.bin"))
    build_rgb_cache(
        lambda: ImageFolderDataset(jpeg_folder, decode_size=24),
        cache_dir,
        canvas_size=24,
        root=jpeg_folder,
    )
    assert os.path.getmtime(os.path.join(cache_dir, "data.bin")) == t0
    ds = PackedRGBCacheDataset(cache_dir, decode_size=24)
    assert ds._canvases is not None and ds._canvases.shape[1] == 24
    src24 = ImageFolderDataset(jpeg_folder, decode_size=24)
    for i in range(0, len(src24), 3):
        a, _ = src24.load(i)
        b, _ = ds.load(i)
        np.testing.assert_array_equal(a, b)


def test_native_raw_crop_parity(both, tmp_path):
    """The C++ raw-cache loader must agree with the PIL resampler to the
    same tolerance as the path-backed native loader (resamplers differ
    slightly; dims/labels are exact)."""
    from moco_tpu.data.native_loader import native_available

    if not native_available():
        pytest.skip("native loader unavailable")
    src, cached_pil = both
    cache_dir = os.path.dirname(cached_pil._data.filename)
    nat = PackedRGBCacheDataset(cache_dir, decode_size=32, use_native=True)
    assert nat._native is not None

    idx = np.arange(len(src))
    np.testing.assert_array_equal(nat.dims(idx), src.dims(idx))
    rng = np.random.default_rng(11)
    dims = src.dims(idx)
    boxes = np.stack(
        [sample_rrc_boxes(rng, dims, scale=(0.2, 1.0)) for _ in range(2)], axis=1
    )
    a_imgs, a_lab = cached_pil.load_crop_batch(idx, boxes, out_size=24)
    b_imgs, b_lab = nat.load_crop_batch(idx, boxes, out_size=24)
    np.testing.assert_array_equal(a_lab, b_lab)
    for i in range(len(idx)):
        for c in range(2):
            diff = np.abs(
                a_imgs[i, c].astype(np.float32) - b_imgs[i, c].astype(np.float32)
            ).mean()
            assert diff < 6.0, f"img {i} crop {c}: mean abs diff {diff}"


def test_flat_data_dir_shares_one_cache(jpeg_folder, tmp_path):
    """A data_dir with no train/ val/ subdirs resolves both splits to the
    same root — they must share ONE cache ('all'), not build two full
    copies of the same pixels."""
    cache_dir = str(tmp_path / "c")
    tr = build_dataset("imagefolder", jpeg_folder, image_size=28, cache_dir=cache_dir)
    ev = build_dataset(
        "imagefolder", jpeg_folder, image_size=28, train=False, cache_dir=cache_dir
    )
    assert os.path.isdir(os.path.join(cache_dir, "all"))
    assert not os.path.isdir(os.path.join(cache_dir, "train"))
    assert not os.path.isdir(os.path.join(cache_dir, "val"))
    assert len(tr) == len(ev)


def test_legacy_flat_cache_reused_not_orphaned(jpeg_folder, tmp_path):
    """A flat-layout cache built under the pre-'all' naming (train/) must
    be reused by the selector, not orphaned by a silent full re-decode
    into all/."""
    cache_dir = str(tmp_path / "c")
    # simulate the legacy layout: flat root cached under 'train'
    build_rgb_cache(
        ImageFolderDataset(jpeg_folder, decode_size=32),
        os.path.join(cache_dir, "train"),
        canvas_size=32,
        root=jpeg_folder,
    )
    ds = build_dataset("imagefolder", jpeg_folder, image_size=28, cache_dir=cache_dir)
    assert isinstance(ds, PackedRGBCacheDataset)
    assert not os.path.isdir(os.path.join(cache_dir, "all"))  # no re-decode
    assert "train" in ds._data.filename


def test_gone_source_with_split_layout_still_served(tmp_path):
    """data_dir with train/ val/ subdirs is deleted after caching: split
    detection degrades, but the surviving stamped cache must be found and
    trusted (the cache is self-contained)."""
    import shutil

    rng = np.random.default_rng(3)
    data_dir = tmp_path / "data"
    for split in ("train", "val"):
        d = data_dir / split / "class_0"
        d.mkdir(parents=True)
        for i in range(3):
            arr = rng.integers(0, 256, (40, 44, 3), dtype=np.uint8)
            Image.fromarray(arr).save(d / f"im_{i}.jpg", quality=92)
    cache_dir = str(tmp_path / "c")
    ds1 = build_dataset(
        "imagefolder", str(data_dir), image_size=28, cache_dir=cache_dir
    )
    n = len(ds1)
    shutil.rmtree(data_dir)
    ds2 = build_dataset(
        "imagefolder", str(data_dir), image_size=28, cache_dir=cache_dir
    )
    assert isinstance(ds2, PackedRGBCacheDataset)
    assert len(ds2) == n
    a, la = ds1.load(0)
    b, lb = ds2.load(0)
    np.testing.assert_array_equal(a, b)
    assert la == lb


def test_legacy_flat_cache_serves_val_split_too(jpeg_folder, tmp_path):
    """Flat layout: BOTH splits must reuse a legacy train/ cache — the
    val-split request must not re-decode into all/."""
    cache_dir = str(tmp_path / "c")
    build_rgb_cache(
        ImageFolderDataset(jpeg_folder, decode_size=32),
        os.path.join(cache_dir, "train"),
        canvas_size=32,
        root=jpeg_folder,
    )
    ev = build_dataset(
        "imagefolder", jpeg_folder, image_size=28, train=False, cache_dir=cache_dir
    )
    assert isinstance(ev, PackedRGBCacheDataset)
    assert not os.path.isdir(os.path.join(cache_dir, "all"))
    assert "train" in ev._data.filename


def test_gone_split_layout_val_request_gets_val_cache(tmp_path):
    """Split layout deleted after caching: a val request must serve the
    val cache, never silently the train one."""
    import shutil

    rng = np.random.default_rng(5)
    data_dir = tmp_path / "data"
    for split, base in (("train", 10), ("val", 200)):
        d = data_dir / split / "class_0"
        d.mkdir(parents=True)
        for i in range(3):
            arr = np.full((40, 44, 3), base + i, np.uint8)
            Image.fromarray(arr).save(d / f"im_{i}.png")
    cache_dir = str(tmp_path / "c")
    build_dataset("imagefolder", str(data_dir), image_size=28, cache_dir=cache_dir)
    ev1 = build_dataset(
        "imagefolder", str(data_dir), image_size=28, train=False, cache_dir=cache_dir
    )
    val_img, _ = ev1.load(0)
    shutil.rmtree(data_dir)
    with pytest.warns(UserWarning, match="does not exist"):
        ev2 = build_dataset(
            "imagefolder", str(data_dir), image_size=28, train=False, cache_dir=cache_dir
        )
    assert "val" in ev2._data.filename
    np.testing.assert_array_equal(ev2.load(0)[0], val_img)
