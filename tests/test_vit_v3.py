"""MoCo v3 ViT: backbone shape/determinism, symmetric train step,
patch-embed freeze, multi-device run.

The v3 variant is queue-free (batch negatives, symmetric 2τ-scaled loss,
prediction head) per arXiv:2104.02057; the reference repo itself is
CNN-only (SURVEY.md §5.7)."""

import jax
import jax.numpy as jnp
import numpy as np
import dataclasses
import pytest

from moco_tpu.core import (
    build_encoder,
    build_predictor,
    create_state,
    make_train_step,
    place_state,
)
from moco_tpu.models import create_vit, sincos_2d_posembed
from moco_tpu.parallel import create_mesh, shard_batch
from moco_tpu.utils.config import DataConfig, MocoConfig, OptimConfig, TrainConfig
from moco_tpu.utils.schedules import build_optimizer
from moco_tpu.parallel.compat import shard_map

IMG = 16  # 4x4 grid of 4px patches


def _v3_config(n_data: int) -> TrainConfig:
    return TrainConfig(
        moco=MocoConfig(
            arch="vit_tiny",
            dim=32,
            num_negatives=0,
            momentum=0.99,
            temperature=0.2,
            v3=True,
            shuffle="none",
            compute_dtype="float32",
            vit_patch_size=4,
        ),
        optim=OptimConfig(optimizer="adamw", lr=1e-3, weight_decay=0.1, epochs=2, cos=True),
        data=DataConfig(dataset="synthetic", image_size=IMG, global_batch=4 * n_data),
    )


def test_vit_forward_shape_and_determinism():
    vit = create_vit("vit_tiny", image_size=IMG, patch_size=4)
    x = jax.random.normal(jax.random.PRNGKey(0), (2, IMG, IMG, 3))
    params = vit.init(jax.random.PRNGKey(1), x)
    out1 = vit.apply(params, x)
    out2 = vit.apply(params, x)
    assert out1.shape == (2, vit.num_features)
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))


def test_sincos_posembed_properties():
    emb = sincos_2d_posembed(64, 4)
    assert emb.shape == (1, 17, 64)
    np.testing.assert_array_equal(emb[0, 0], np.zeros(64))  # cls slot
    # distinct positions get distinct embeddings
    assert not np.allclose(emb[0, 1], emb[0, 2])


@pytest.fixture(scope="module")
def v3_setup():
    n_data = 2
    config = _v3_config(n_data)
    mesh = create_mesh(num_data=n_data, num_model=1, devices=jax.devices()[:n_data])
    encoder = build_encoder(config.moco, num_data=n_data)
    predictor = build_predictor(config.moco, num_data=n_data)
    assert predictor is not None
    tx = build_optimizer(config.optim, steps_per_epoch=4)
    sample = jnp.zeros((1, IMG, IMG, 3), jnp.float32)
    state = create_state(jax.random.PRNGKey(0), config, encoder, tx, sample, predictor=predictor)
    state = place_state(state, mesh)
    step = make_train_step(config, encoder, tx, mesh, predictor=predictor)
    batch = {
        "im_q": jax.random.normal(jax.random.PRNGKey(1), (8, IMG, IMG, 3)),
        "im_k": jax.random.normal(jax.random.PRNGKey(2), (8, IMG, IMG, 3)),
    }
    batch = shard_batch(mesh, batch)
    rng = jax.device_put(
        jax.random.PRNGKey(3), jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec())
    )
    return config, state, step, batch, rng


def test_v3_step_runs_and_is_finite(v3_setup):
    config, state, step, batch, rng = v3_setup
    new_state, metrics = step(state, batch, rng)
    assert int(new_state.step) == 1
    assert np.isfinite(float(metrics["loss"]))
    assert 0 <= float(metrics["acc1"]) <= 100


def test_v3_patch_embed_frozen(v3_setup):
    config, state, step, batch, rng = v3_setup
    new_state, _ = step(state, batch, rng)
    before = jax.tree.leaves(state.params_q["backbone"]["patch_embed"])
    after = jax.tree.leaves(new_state.params_q["backbone"]["patch_embed"])
    for a, b in zip(before, after):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # but the transformer blocks DID train
    changed = any(
        not np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(
            jax.tree.leaves(state.params_q["backbone"]["block_0"]),
            jax.tree.leaves(new_state.params_q["backbone"]["block_0"]),
        )
    )
    assert changed


def test_v3_key_encoder_is_ema(v3_setup):
    config, state, step, batch, rng = v3_setup
    new_state, _ = step(state, batch, rng)
    m = config.moco.momentum
    q0 = jax.tree.leaves(state.params_q)[0]
    k0 = jax.tree.leaves(state.params_k)[0]
    k1 = jax.tree.leaves(new_state.params_k)[0]
    np.testing.assert_allclose(
        np.asarray(k1), np.asarray(k0) * m + np.asarray(q0) * (1 - m), rtol=1e-5
    )


def test_momentum_cos_requires_total_steps():
    import dataclasses as dc

    config = _v3_config(1)
    config = dc.replace(config, moco=dc.replace(config.moco, momentum_cos=True))
    mesh = create_mesh(num_data=1, num_model=1, devices=jax.devices()[:1])
    encoder = build_encoder(config.moco, num_data=1)
    predictor = build_predictor(config.moco, num_data=1)
    tx = build_optimizer(config.optim, steps_per_epoch=4)
    with pytest.raises(ValueError, match="total_steps"):
        make_train_step(config, encoder, tx, mesh, predictor=predictor)
    # with total_steps it builds fine
    make_train_step(config, encoder, tx, mesh, predictor=predictor, total_steps=8)


def test_v3_head_shapes_per_backbone_family():
    """Upstream moco-v3 `_build_projector_and_predictor_mlps`: ResNet
    gets a 2-layer projector + predictor WITHOUT the final BN; ViT gets
    the 3-layer projector + predictor ending in affine-free BN."""
    from moco_tpu.models import V3MLPHead

    r_cfg = MocoConfig(
        arch="resnet18", dim=32, num_negatives=0, v3=True,
        shuffle="none", cifar_stem=True, compute_dtype="float32",
    )
    r_enc = build_encoder(r_cfg, num_data=1)
    r_pred = build_predictor(r_cfg, num_data=1)
    assert isinstance(r_enc.head, V3MLPHead)
    assert r_enc.head.num_layers == 2 and r_enc.head.last_bn
    assert r_pred.num_layers == 2 and not r_pred.last_bn
    # predictor without last_bn really has no BN after the output Dense
    pv = r_pred.init(jax.random.PRNGKey(0), jnp.zeros((2, 32)), train=False)
    n_bn = sum(1 for k in pv["params"] if k.startswith("BatchNorm"))
    assert n_bn == 1  # only the hidden layer's BN

    v_cfg = MocoConfig(
        arch="vit_tiny", dim=32, num_negatives=0, v3=True,
        shuffle="none", compute_dtype="float32", vit_patch_size=4,
    )
    v_enc = build_encoder(v_cfg, num_data=1)
    v_pred = build_predictor(v_cfg, num_data=1)
    assert v_enc.head.num_layers == 3 and v_enc.head.last_bn
    assert v_pred.num_layers == 2 and v_pred.last_bn


def test_v3_predictor_trains(v3_setup):
    config, state, step, batch, rng = v3_setup
    new_state, _ = step(state, batch, rng)
    changed = any(
        not np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree.leaves(state.params_pred), jax.tree.leaves(new_state.params_pred))
    )
    assert changed


def test_vit_flash_attention_matches_dense():
    """use_flash_attention swaps the compute but not the param tree:
    identical params, near-identical output (fp32, interpret kernel).
    Uses a 32px/4px-patch grid -> 65 tokens (odd, exercises padding+mask
    via the dense short-seq path) and a 4-block seq via block override is
    covered in tests/test_flash_attention.py; here the wiring is under test."""
    vit_dense = create_vit("vit_tiny", image_size=32, patch_size=4)
    vit_flash = create_vit(
        "vit_tiny", image_size=32, patch_size=4, use_flash_attention=True
    )
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 32, 32, 3))
    params = vit_dense.init(jax.random.PRNGKey(1), x)
    # same param tree: flash params init to identical structure
    params_flash = vit_flash.init(jax.random.PRNGKey(1), x)
    assert jax.tree.structure(params) == jax.tree.structure(params_flash)
    out_dense = vit_dense.apply(params, x)
    out_flash = vit_flash.apply(params, x)  # dense-trained params, flash compute
    np.testing.assert_allclose(
        np.asarray(out_dense), np.asarray(out_flash), rtol=2e-4, atol=2e-4
    )


class TestSequenceParallelViT:
    """Sequence parallelism: tokens sharded over the mesh's model axis,
    ring attention across shards (the long-context path, SURVEY.md §5.7
    'beyond reference'). Parity against the dense single-device ViT."""

    def _vit(self, **kw):
        return create_vit("vit_tiny", image_size=32, patch_size=4, pool="gap", **kw)

    def test_forward_matches_dense(self):
        from jax.sharding import PartitionSpec as P

        mesh = create_mesh(num_data=1, num_model=8)
        vit_sp = self._vit(sequence_axis="model")
        vit_dense = self._vit()
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 32, 32, 3))
        params = vit_dense.init(jax.random.PRNGKey(1), x)
        # identical param trees: SP is a compute-path choice, not a model
        assert jax.tree.structure(params) == jax.tree.structure(
            vit_sp.init(jax.random.PRNGKey(1), x)
        )
        want = vit_dense.apply(params, x)

        def fwd(params, x):
            return vit_sp.apply(params, x)

        got = jax.jit(
            shard_map(
                fwd, mesh=mesh, in_specs=(P(), P()), out_specs=P(), check_vma=False
            )
        )(params, x)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4)

    def test_outside_shard_map_falls_back_dense(self):
        vit_sp = self._vit(sequence_axis="model")
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 32, 32, 3))
        params = vit_sp.init(jax.random.PRNGKey(1), x)
        out = vit_sp.apply(params, x)  # no axis bound -> dense path
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(self._vit().apply(params, x)), rtol=1e-5, atol=1e-5
        )

    def _sp_config(self, num_model: int) -> TrainConfig:
        cfg = _v3_config(4)
        return dataclasses.replace(
            cfg,
            moco=dataclasses.replace(
                cfg.moco, vit_pool="gap", vit_sequence_parallel=num_model > 0
            ),
        )

    @pytest.mark.slow  # full v3 SP step over the 8-dev mesh: heaviest compile in the suite
    def test_v3_train_step_with_sp_matches_dense(self):
        """One v3 step on a (4, 2) mesh with token-sharded ViT == the same
        step on (4, 1) dense — loss and updated params agree."""
        results = {}
        for num_model in (1, 2):
            config = self._sp_config(num_model if num_model > 1 else 0)
            mesh = create_mesh(num_data=4, num_model=num_model)
            encoder = build_encoder(config.moco, num_data=4)
            predictor = build_predictor(config.moco, num_data=4)
            from moco_tpu.utils.schedules import build_optimizer

            tx = build_optimizer(config.optim, steps_per_epoch=2)
            from moco_tpu.core import create_state, make_train_step, place_state

            sample = jnp.zeros((1, IMG, IMG, 3), jnp.float32)
            state = create_state(
                jax.random.PRNGKey(0), config, encoder, tx, sample, predictor=predictor
            )
            state = place_state(state, mesh)
            step = make_train_step(
                config, encoder, tx, mesh, predictor=predictor, total_steps=4
            )
            ims = jax.random.normal(jax.random.PRNGKey(5), (2, 16, IMG, IMG, 3))
            batch = shard_batch(mesh, {"im_q": ims[0], "im_k": ims[1]})
            rng = jax.device_put(
                jax.random.PRNGKey(7),
                jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec()),
            )
            new_state, metrics = step(state, batch, rng)
            results[num_model] = (
                float(metrics["loss"]),
                np.asarray(
                    jax.tree.leaves(new_state.params_q)[0], dtype=np.float64
                ),
            )
        loss_dense, leaf_dense = results[1]
        loss_sp, leaf_sp = results[2]
        assert np.isfinite(loss_sp)
        np.testing.assert_allclose(loss_sp, loss_dense, rtol=1e-4)
        np.testing.assert_allclose(leaf_sp, leaf_dense, rtol=1e-3, atol=1e-5)


def test_vit_grouped_apply_matches_whole_bitwise():
    """Layer-granular ZeRO-3 seam (ISSUE 20): embed -> block_i... ->
    final, each applied with only its own param children, reproduces the
    whole-model forward BIT-identically, and the group->child map tiles
    the param tree exactly."""
    vit = create_vit("vit_tiny", image_size=IMG, patch_size=4)
    x = jax.random.normal(jax.random.PRNGKey(0), (2, IMG, IMG, 3))
    variables = vit.init(jax.random.PRNGKey(1), x)
    whole = vit.apply(variables, x, train=True)

    names = vit.group_param_names()
    claimed = [c for g in vit.group_names for c in names[g]]
    assert sorted(claimed) == sorted(variables["params"].keys())

    out = x
    for g in vit.group_names:
        params_g = {k: variables["params"][k] for k in names[g]}
        out = vit.apply({"params": params_g}, out, train=True, group=g)
    np.testing.assert_array_equal(np.asarray(whole), np.asarray(out))

    with pytest.raises(ValueError, match="unknown layer group"):
        vit.apply(variables, x, train=True, group="block_99")
    # grouped apply + sequence parallelism would shard tokens across
    # group boundaries: rejected at the module gate
    sp = create_vit(
        "vit_tiny", image_size=IMG, patch_size=4, sequence_axis="model"
    )
    vsp = sp.init(jax.random.PRNGKey(1), x)
    with pytest.raises(ValueError, match="sequence_axis"):
        sp.apply(vsp, x, train=True, group="embed")
