"""Multi-host data partition: the DistributedSampler equivalent
(`main_moco.py:~L258`). Verifies the per-process index partition is
disjoint, exhaustive, deterministic, replica-aware, and that per-shard
assembly reproduces a plain sharded device_put — all on the 8-virtual-
device CPU mesh, simulating process boundaries with the
`addressable_devices` override."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from moco_tpu.parallel import (
    ProcessDataPartition,
    create_mesh,
    device_row_ranges,
    shard_batch,
)

B = 16


def _sharding(num_data, num_model=1):
    mesh = create_mesh(num_data=num_data, num_model=num_model)
    return NamedSharding(mesh, P("data"))


def _fake_processes(sharding, n_proc):
    """Split the mesh devices into n_proc contiguous 'hosts'."""
    devs = sorted(sharding.mesh.devices.flatten().tolist(), key=lambda d: d.id)
    per = len(devs) // n_proc
    return [devs[i * per : (i + 1) * per] for i in range(n_proc)]


def test_single_process_holds_all_rows():
    part = ProcessDataPartition(_sharding(8), B)
    assert part.is_trivial
    np.testing.assert_array_equal(part.local_positions, np.arange(B))


def test_partition_disjoint_exhaustive_across_processes():
    sharding = _sharding(8)
    parts = [
        ProcessDataPartition(sharding, B, addressable_devices=procs)
        for procs in _fake_processes(sharding, 4)
    ]
    all_rows = np.concatenate([p.local_positions for p in parts])
    # disjoint + exhaustive: exactly [0, B) with no repeats
    np.testing.assert_array_equal(np.sort(all_rows), np.arange(B))
    for p in parts:
        assert p.local_rows == B // 4


def test_partition_deterministic():
    sharding = _sharding(8)
    procs = _fake_processes(sharding, 2)[0]
    a = ProcessDataPartition(sharding, B, addressable_devices=procs)
    b = ProcessDataPartition(sharding, B, addressable_devices=procs)
    np.testing.assert_array_equal(a.local_positions, b.local_positions)


def test_replicas_share_rows_over_model_axis():
    # (4, 2) mesh: model-axis replicas of a row range live on 2 devices,
    # but the host decodes each row ONCE
    sharding = _sharding(4, num_model=2)
    ranges = device_row_ranges(sharding, B)
    assert len(ranges) == 8 and len(set(ranges.values())) == 4
    part = ProcessDataPartition(sharding, B)
    assert part.local_rows == B  # every unique row once, not 2x


def test_local_indices_map_epoch_order():
    sharding = _sharding(8)
    proc1 = _fake_processes(sharding, 2)[1]
    part = ProcessDataPartition(sharding, B, addressable_devices=proc1)
    order = np.random.default_rng(0).permutation(100)[:B]
    np.testing.assert_array_equal(
        part.local_indices(order), order[part.local_positions]
    )


def test_assemble_matches_plain_device_put():
    sharding = _sharding(8)
    part = ProcessDataPartition(sharding, B)
    data = np.random.default_rng(1).normal(size=(B, 4, 4, 3)).astype(np.float32)
    assembled = part.assemble(data)
    expected = shard_batch(sharding.mesh, jnp.asarray(data))
    assert assembled.sharding.is_equivalent_to(expected.sharding, assembled.ndim)
    np.testing.assert_array_equal(np.asarray(assembled), np.asarray(expected))
    # and it is consumable by a jitted sharded reduction
    out = jax.jit(lambda x: x.sum())(assembled)
    np.testing.assert_allclose(float(out), data.sum(), rtol=1e-5)


def test_assemble_from_simulated_hosts_roundtrips():
    """Union of every fake host's shards reconstructs the global batch."""
    sharding = _sharding(8)
    data = np.arange(B * 2, dtype=np.float32).reshape(B, 2)
    pieces = {}
    for procs in _fake_processes(sharding, 4):
        part = ProcessDataPartition(sharding, B, addressable_devices=procs)
        local = data[part.local_positions]
        for pos, row in zip(part.local_positions, local):
            pieces[int(pos)] = row
    rebuilt = np.stack([pieces[i] for i in range(B)])
    np.testing.assert_array_equal(rebuilt, data)


def test_assemble_wrong_rowcount_raises():
    part = ProcessDataPartition(_sharding(8), B)
    try:
        part.assemble(np.zeros((B + 1, 2), np.float32))
    except ValueError as e:
        assert "local rows" in str(e)
    else:
        raise AssertionError("expected ValueError")


class TestMultisliceMesh:
    """create_multislice_mesh logic (slice counting, per-slice shape
    math, DCN-outer layout) — hardware-independent via a stubbed
    mesh_utils; no multi-slice TPU exists in CI."""

    class _FakeDev:
        def __init__(self, slice_index):
            self.slice_index = slice_index

    def test_single_slice_falls_back_to_flat_mesh(self):
        from moco_tpu.parallel.mesh import create_multislice_mesh

        mesh = create_multislice_mesh()
        assert mesh.shape["data"] == len(jax.devices())
        assert mesh.shape["model"] == 1

    def test_hybrid_shapes_passed_to_mesh_utils(self, monkeypatch):
        import moco_tpu.parallel.mesh as mesh_mod
        from jax.experimental import mesh_utils

        real = jax.devices()  # 8 virtual CPU devices
        fakes = [self._FakeDev(i // 4) for i in range(8)]  # 2 slices x 4
        monkeypatch.setattr(jax, "devices", lambda: fakes)
        seen = {}

        def stub(mesh_shape, dcn_mesh_shape, devices):
            seen["mesh_shape"] = mesh_shape
            seen["dcn_mesh_shape"] = dcn_mesh_shape
            total = int(np.prod(mesh_shape)) * int(np.prod(dcn_mesh_shape))
            shape = (dcn_mesh_shape[0] * mesh_shape[0], mesh_shape[1])
            return np.array(real[:total]).reshape(shape)

        monkeypatch.setattr(mesh_utils, "create_hybrid_device_mesh", stub)
        mesh = mesh_mod.create_multislice_mesh(num_model=2)
        # per slice: 4 chips / model 2 -> data 2; DCN outer: 2 slices
        assert seen["mesh_shape"] == (2, 2)
        assert seen["dcn_mesh_shape"] == (2, 1)
        assert mesh.shape["data"] == 4 and mesh.shape["model"] == 2

    def test_model_not_dividing_slice_raises(self, monkeypatch):
        import moco_tpu.parallel.mesh as mesh_mod

        fakes = [self._FakeDev(i // 4) for i in range(8)]
        monkeypatch.setattr(jax, "devices", lambda: fakes)
        with pytest.raises(ValueError, match="not divisible"):
            mesh_mod.create_multislice_mesh(num_model=3)
