"""Meters + JSONL writer (reference `AverageMeter`/`ProgressMeter`,
`main_moco.py:~L322-360`)."""

import json

from moco_tpu.utils.metrics import AverageMeter, MetricWriter, ProgressMeter


def test_average_meter_matches_reference_semantics():
    m = AverageMeter("Loss", ":.4e")
    m.update(2.0, n=2)
    m.update(4.0, n=2)
    assert m.val == 4.0
    assert m.avg == 3.0
    assert "Loss" in str(m)


def test_progress_meter_line_format():
    m = AverageMeter("Acc@1", ":6.2f")
    m.update(12.5)
    p = ProgressMeter(100, [m], prefix="Epoch: [3]")
    line = p.display(7)
    assert line.startswith("Epoch: [3][  7/100]")
    assert "Acc@1" in line


def test_metric_writer_jsonl(tmp_path):
    w = MetricWriter(str(tmp_path))
    w.write(5, {"loss": 1.5, "lr": 0.03})
    w.write(10, {"loss": 1.2})
    w.close()
    lines = [json.loads(l) for l in open(w.path)]
    assert lines[0]["step"] == 5 and lines[0]["loss"] == 1.5
    assert lines[1]["step"] == 10


def test_metric_writer_flushes_each_line(tmp_path):
    """Crash-safety (fault-tolerance layer): a written line must be
    visible in the file BEFORE close — a SIGKILL mid-epoch cannot lose
    the metrics tail the retry/guard counters land in."""
    w = MetricWriter(str(tmp_path))
    w.write(1, {"loss": 2.0})
    w.write(2, {"loss": 1.9, "io_retries": {"data.read": 3}})
    lines = [json.loads(l) for l in open(w.path)]  # no close() yet
    assert len(lines) == 2
    assert lines[1]["io_retries"] == {"data.read": 3}
    w.fsync()  # durable tail (preemption path); idempotent with close
    w.close()
    w.close()  # double-close must be safe (driver finally + tests)


def test_metric_writer_sanitizes_non_finite(tmp_path):
    """NaN/Inf are invalid JSON; they become null so the file stays
    strict-JSONL-parseable (the guard writes its own explicit event)."""
    w = MetricWriter(str(tmp_path))
    w.write(1, {"loss": float("nan"), "acc1": float("inf"), "lr": 0.1})
    w.close()
    rec = json.loads(open(w.path).read())
    assert rec["loss"] is None and rec["acc1"] is None and rec["lr"] == 0.1
