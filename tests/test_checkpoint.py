"""Checkpoint round-trip: full MocoState (queue, EMA, opt_state) +
resume semantics, the rebuild's answer to `--resume` (SURVEY.md §3.5) —
plus the fault-tolerance layer: corrupt-latest fallback, quarantine,
and the fail-fast resume compatibility check."""

import dataclasses
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from moco_tpu.core import build_encoder, create_state
from moco_tpu.utils import faults
from moco_tpu.utils.checkpoint import (
    CheckpointCorruptionError,
    CheckpointManager,
    restore_best,
    save_best,
)
from moco_tpu.utils.config import (
    DataConfig,
    MocoConfig,
    OptimConfig,
    ResumeCompatError,
    TrainConfig,
    config_to_dict,
    resume_compat_diff,
)
from moco_tpu.utils.schedules import build_optimizer


@pytest.fixture(scope="module")
def small_state():
    config = TrainConfig(
        moco=MocoConfig(
            arch="resnet18", dim=16, num_negatives=64, cifar_stem=True,
            shuffle="none", compute_dtype="float32",
        ),
        optim=OptimConfig(lr=0.03, epochs=2),
        data=DataConfig(dataset="synthetic", image_size=16, global_batch=8),
    )
    encoder = build_encoder(config.moco)
    tx = build_optimizer(config.optim, steps_per_epoch=4)
    state = create_state(
        jax.random.PRNGKey(0), config, encoder, tx, jnp.zeros((1, 16, 16, 3))
    )
    return state


def _assert_trees_equal(a, b):
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_roundtrip_preserves_full_state(tmp_path, small_state):
    state = small_state.replace(
        step=jnp.asarray(7, jnp.int32),
        queue_ptr=jnp.asarray(16, jnp.int32),
    )
    mgr = CheckpointManager(str(tmp_path / "ckpt"), keep=2)
    mgr.save(1, state, extra={"epoch": 1, "rng": np.asarray([1, 2], np.uint32)})
    restored, extra = mgr.restore(small_state)
    _assert_trees_equal(state, restored)
    assert extra["epoch"] == 1
    assert int(restored.queue_ptr) == 16
    mgr.close()


def test_keep_last_n_and_latest(tmp_path, small_state):
    mgr = CheckpointManager(str(tmp_path / "ckpt"), keep=2)
    for e in (1, 2, 3):
        mgr.save(e, small_state, extra={"epoch": e})
    assert mgr.latest_step() == 3
    _, extra = mgr.restore(small_state)
    assert extra["epoch"] == 3
    # step 1 should have been garbage-collected
    with pytest.raises(Exception):
        mgr.restore(small_state, step=1)
    mgr.close()


def test_restore_errors_when_empty(tmp_path, small_state):
    mgr = CheckpointManager(str(tmp_path / "empty"))
    with pytest.raises(FileNotFoundError):
        mgr.restore(small_state)
    mgr.close()


def test_best_snapshot(tmp_path, small_state):
    save_best(str(tmp_path), small_state, metric=61.25)
    restored, metric = restore_best(str(tmp_path), small_state)
    _assert_trees_equal(small_state, restored)
    assert metric == 61.25


def _truncate_state_file(directory, step):
    """Simulate a torn write: halve the largest file under the step's
    state/ payload (commit metadata stays — the dir looks complete)."""
    state_dir = os.path.join(directory, str(step), "state")
    files = [
        os.path.join(root, f)
        for root, _, names in os.walk(state_dir)
        for f in names
    ]
    target = max(files, key=os.path.getsize)
    with open(target, "r+b") as f:
        f.truncate(os.path.getsize(target) // 2)


def test_corrupt_latest_falls_back_and_quarantines(tmp_path, small_state):
    """The tentpole behavior: a corrupt newest checkpoint costs one
    checkpoint interval, not the run — it is quarantined and the
    next-older step restores."""
    d = str(tmp_path / "ckpt")
    mgr = CheckpointManager(d, keep=3)
    mgr.save(1, small_state, extra={"epoch": 0})
    mgr.save(2, small_state, extra={"epoch": 1})
    _truncate_state_file(d, 2)
    restored, extra = mgr.restore(small_state)
    assert extra["epoch"] == 0  # fell back to step 1
    _assert_trees_equal(restored, small_state)
    assert os.path.isdir(os.path.join(d, "quarantine", "2"))
    assert not os.path.exists(os.path.join(d, "2"))
    assert mgr.latest_step() == 1
    # the manager still accepts new saves after a quarantine
    mgr.save(3, small_state, extra={"epoch": 2})
    _, extra = mgr.restore(small_state)
    assert extra["epoch"] == 2
    mgr.close()


def test_all_corrupt_raises_corruption_error(tmp_path, small_state):
    """Every checkpoint bad -> loud CheckpointCorruptionError, never a
    silent fresh start."""
    d = str(tmp_path / "ckpt")
    mgr = CheckpointManager(d)
    mgr.save(1, small_state, extra={"epoch": 0})
    _truncate_state_file(d, 1)
    with pytest.raises(CheckpointCorruptionError):
        mgr.restore(small_state)
    assert os.path.isdir(os.path.join(d, "quarantine", "1"))
    mgr.close()


def test_explicit_step_restore_does_not_fall_back(tmp_path, small_state):
    d = str(tmp_path / "ckpt")
    mgr = CheckpointManager(d)
    mgr.save(1, small_state, extra={"epoch": 0})
    mgr.save(2, small_state, extra={"epoch": 1})
    _truncate_state_file(d, 2)
    with pytest.raises(Exception) as e:
        mgr.restore(small_state, step=2)
    assert not isinstance(e.value, CheckpointCorruptionError)
    assert os.path.exists(os.path.join(d, "2"))  # no quarantine either
    mgr.close()


def test_latest_step_skips_torn_write(tmp_path, small_state):
    """Structural validation: a zero-length payload file (torn write)
    disqualifies the step without a full restore."""
    d = str(tmp_path / "ckpt")
    mgr = CheckpointManager(d)
    mgr.save(1, small_state, extra={"epoch": 0})
    mgr.save(2, small_state, extra={"epoch": 1})
    state_dir = os.path.join(d, "2", "state")
    files = [
        os.path.join(root, f)
        for root, _, names in os.walk(state_dir)
        for f in names
    ]
    with open(max(files, key=os.path.getsize), "r+b") as f:
        f.truncate(0)
    assert mgr.latest_step() == 1
    assert os.path.isdir(os.path.join(d, "quarantine", "2"))
    mgr.close()


def test_validate_extra_incompat_fails_fast_without_quarantine(tmp_path, small_state):
    """Config drift is a user error, not corruption: it must raise with
    the diff BEFORE the state restore and must not quarantine anything."""
    d = str(tmp_path / "ckpt")
    mgr = CheckpointManager(d)
    mgr.save(1, small_state, extra={"epoch": 0, "config": {"moco": {"dim": 16}}})

    def reject(extra):
        raise ResumeCompatError(f"incompatible: {extra['config']}")

    with pytest.raises(ResumeCompatError):
        mgr.restore(small_state, validate_extra=reject)
    assert os.path.exists(os.path.join(d, "1"))
    assert not os.path.isdir(os.path.join(d, "quarantine"))
    mgr.close()


def test_ckpt_truncate_fault_injection_roundtrip(tmp_path, small_state):
    """The chaos harness's checkpoint fault composes with the fallback
    restore: the faulted save is corrupted on disk, restore falls back."""
    d = str(tmp_path / "ckpt")
    mgr = CheckpointManager(d)
    faults.install("ckpt_truncate@step=2")
    try:
        mgr.save(1, small_state, extra={"epoch": 0})
        mgr.save(2, small_state, extra={"epoch": 1})  # truncated by the fault
    finally:
        faults.clear()
    _, extra = mgr.restore(small_state)
    assert extra["epoch"] == 0
    assert os.path.isdir(os.path.join(d, "quarantine", "2"))
    mgr.close()


def test_resume_compat_diff_fields():
    cfg = TrainConfig()
    saved = {"config": config_to_dict(cfg), "num_data": 8}
    assert resume_compat_diff(saved, cfg, 8) == []
    # structural drift is caught, field by field
    cfg2 = dataclasses.replace(
        cfg, moco=dataclasses.replace(cfg.moco, arch="resnet50x", dim=256)
    )
    diffs = resume_compat_diff(saved, cfg2, 8)
    assert any("moco.arch" in s for s in diffs)
    assert any("moco.dim" in s for s in diffs)
    # tunables may change freely across a resume
    cfg3 = dataclasses.replace(
        cfg, optim=dataclasses.replace(cfg.optim, lr=9.9, epochs=500)
    )
    assert resume_compat_diff(saved, cfg3, 8) == []
    # ZeRO layout fields (shard_weight_update / zero_stage / mesh
    # width) are "compatible but resharded" since ISSUE 7 — the driver
    # restores into the checkpoint's own layout and converts
    # (core/moco.py:reshard_state), so they produce NO hard diff
    zcfg = dataclasses.replace(
        cfg, parallel=dataclasses.replace(cfg.parallel, shard_weight_update=True)
    )
    zsaved = {"config": config_to_dict(zcfg), "num_data": 8}
    assert resume_compat_diff(zsaved, zcfg, 4) == []  # resharded, not rejected
    assert resume_compat_diff(zsaved, cfg, 8) == []  # sharded -> replicated: free
    assert resume_compat_diff(saved, cfg, 4) == []  # non-ZeRO: free
    # ...but num_model stays structural (queue sharding changes shapes)
    mcfg = dataclasses.replace(
        cfg, parallel=dataclasses.replace(cfg.parallel, num_model=2)
    )
    assert any("num_model" in s for s in resume_compat_diff(saved, mcfg, 8))
    # pre-layer checkpoints (no config recorded) stay resumable
    assert resume_compat_diff({"epoch": 3}, cfg2, 8) == []


def test_async_save_roundtrips_and_waits(tmp_path, small_state):
    """Async saves overlap with training; restore/wait must first land
    any in-flight write, and the round-trip is bit-identical."""
    mgr = CheckpointManager(str(tmp_path / "async"), async_save=True)
    mgr.save(1, small_state, extra={"epoch": 0})
    mgr.save(2, small_state, extra={"epoch": 1}, force=True)
    mgr.wait()
    restored, extra = mgr.restore(small_state)
    assert extra["epoch"] == 1
    _assert_trees_equal(restored, small_state)
    # restore without an explicit wait must also be safe mid-flight
    mgr.save(3, small_state, extra={"epoch": 2}, force=True)
    restored, extra = mgr.restore(small_state)
    assert extra["epoch"] == 2
    mgr.close()


@pytest.mark.slow  # full train-driver cycle: minutes on a CPU host
def test_async_driver_run_resumes(tmp_path):
    """The pretrain driver with checkpoint_async=True survives a full
    train() + auto-resume cycle."""
    import dataclasses

    from moco_tpu.data.datasets import SyntheticDataset
    from moco_tpu.train import train
    from moco_tpu.utils.config import DataConfig, MocoConfig, OptimConfig, TrainConfig

    config = TrainConfig(
        moco=MocoConfig(
            arch="resnet18", dim=16, num_negatives=32, mlp=True,
            shuffle="none", cifar_stem=True, compute_dtype="float32",
        ),
        optim=OptimConfig(lr=0.03, epochs=1, cos=True),
        data=DataConfig(dataset="synthetic", image_size=16, global_batch=16, num_workers=2),
        workdir=str(tmp_path / "pre_async"),
        log_every=100,
        checkpoint_async=True,
    )
    dataset = SyntheticDataset(num_examples=32, image_size=16)
    train(config, dataset=dataset)
    # second run resumes from the async-written checkpoint
    config2 = dataclasses.replace(
        config, optim=dataclasses.replace(config.optim, epochs=2)
    )
    out = train(config2, dataset=dataset)
    assert out["epoch"] == 1
