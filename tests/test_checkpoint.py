"""Checkpoint round-trip: full MocoState (queue, EMA, opt_state) +
resume semantics, the rebuild's answer to `--resume` (SURVEY.md §3.5)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from moco_tpu.core import build_encoder, create_state
from moco_tpu.utils.checkpoint import CheckpointManager, restore_best, save_best
from moco_tpu.utils.config import DataConfig, MocoConfig, OptimConfig, TrainConfig
from moco_tpu.utils.schedules import build_optimizer


@pytest.fixture(scope="module")
def small_state():
    config = TrainConfig(
        moco=MocoConfig(
            arch="resnet18", dim=16, num_negatives=64, cifar_stem=True,
            shuffle="none", compute_dtype="float32",
        ),
        optim=OptimConfig(lr=0.03, epochs=2),
        data=DataConfig(dataset="synthetic", image_size=16, global_batch=8),
    )
    encoder = build_encoder(config.moco)
    tx = build_optimizer(config.optim, steps_per_epoch=4)
    state = create_state(
        jax.random.PRNGKey(0), config, encoder, tx, jnp.zeros((1, 16, 16, 3))
    )
    return state


def _assert_trees_equal(a, b):
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_roundtrip_preserves_full_state(tmp_path, small_state):
    state = small_state.replace(
        step=jnp.asarray(7, jnp.int32),
        queue_ptr=jnp.asarray(16, jnp.int32),
    )
    mgr = CheckpointManager(str(tmp_path / "ckpt"), keep=2)
    mgr.save(1, state, extra={"epoch": 1, "rng": np.asarray([1, 2], np.uint32)})
    restored, extra = mgr.restore(small_state)
    _assert_trees_equal(state, restored)
    assert extra["epoch"] == 1
    assert int(restored.queue_ptr) == 16
    mgr.close()


def test_keep_last_n_and_latest(tmp_path, small_state):
    mgr = CheckpointManager(str(tmp_path / "ckpt"), keep=2)
    for e in (1, 2, 3):
        mgr.save(e, small_state, extra={"epoch": e})
    assert mgr.latest_step() == 3
    _, extra = mgr.restore(small_state)
    assert extra["epoch"] == 3
    # step 1 should have been garbage-collected
    with pytest.raises(Exception):
        mgr.restore(small_state, step=1)
    mgr.close()


def test_restore_errors_when_empty(tmp_path, small_state):
    mgr = CheckpointManager(str(tmp_path / "empty"))
    with pytest.raises(FileNotFoundError):
        mgr.restore(small_state)
    mgr.close()


def test_best_snapshot(tmp_path, small_state):
    save_best(str(tmp_path), small_state, metric=61.25)
    restored, metric = restore_best(str(tmp_path), small_state)
    _assert_trees_equal(small_state, restored)
    assert metric == 61.25
