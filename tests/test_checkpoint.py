"""Checkpoint round-trip: full MocoState (queue, EMA, opt_state) +
resume semantics, the rebuild's answer to `--resume` (SURVEY.md §3.5)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from moco_tpu.core import build_encoder, create_state
from moco_tpu.utils.checkpoint import CheckpointManager, restore_best, save_best
from moco_tpu.utils.config import DataConfig, MocoConfig, OptimConfig, TrainConfig
from moco_tpu.utils.schedules import build_optimizer


@pytest.fixture(scope="module")
def small_state():
    config = TrainConfig(
        moco=MocoConfig(
            arch="resnet18", dim=16, num_negatives=64, cifar_stem=True,
            shuffle="none", compute_dtype="float32",
        ),
        optim=OptimConfig(lr=0.03, epochs=2),
        data=DataConfig(dataset="synthetic", image_size=16, global_batch=8),
    )
    encoder = build_encoder(config.moco)
    tx = build_optimizer(config.optim, steps_per_epoch=4)
    state = create_state(
        jax.random.PRNGKey(0), config, encoder, tx, jnp.zeros((1, 16, 16, 3))
    )
    return state


def _assert_trees_equal(a, b):
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_roundtrip_preserves_full_state(tmp_path, small_state):
    state = small_state.replace(
        step=jnp.asarray(7, jnp.int32),
        queue_ptr=jnp.asarray(16, jnp.int32),
    )
    mgr = CheckpointManager(str(tmp_path / "ckpt"), keep=2)
    mgr.save(1, state, extra={"epoch": 1, "rng": np.asarray([1, 2], np.uint32)})
    restored, extra = mgr.restore(small_state)
    _assert_trees_equal(state, restored)
    assert extra["epoch"] == 1
    assert int(restored.queue_ptr) == 16
    mgr.close()


def test_keep_last_n_and_latest(tmp_path, small_state):
    mgr = CheckpointManager(str(tmp_path / "ckpt"), keep=2)
    for e in (1, 2, 3):
        mgr.save(e, small_state, extra={"epoch": e})
    assert mgr.latest_step() == 3
    _, extra = mgr.restore(small_state)
    assert extra["epoch"] == 3
    # step 1 should have been garbage-collected
    with pytest.raises(Exception):
        mgr.restore(small_state, step=1)
    mgr.close()


def test_restore_errors_when_empty(tmp_path, small_state):
    mgr = CheckpointManager(str(tmp_path / "empty"))
    with pytest.raises(FileNotFoundError):
        mgr.restore(small_state)
    mgr.close()


def test_best_snapshot(tmp_path, small_state):
    save_best(str(tmp_path), small_state, metric=61.25)
    restored, metric = restore_best(str(tmp_path), small_state)
    _assert_trees_equal(small_state, restored)
    assert metric == 61.25


def test_async_save_roundtrips_and_waits(tmp_path, small_state):
    """Async saves overlap with training; restore/wait must first land
    any in-flight write, and the round-trip is bit-identical."""
    mgr = CheckpointManager(str(tmp_path / "async"), async_save=True)
    mgr.save(1, small_state, extra={"epoch": 0})
    mgr.save(2, small_state, extra={"epoch": 1}, force=True)
    mgr.wait()
    restored, extra = mgr.restore(small_state)
    assert extra["epoch"] == 1
    _assert_trees_equal(restored, small_state)
    # restore without an explicit wait must also be safe mid-flight
    mgr.save(3, small_state, extra={"epoch": 2}, force=True)
    restored, extra = mgr.restore(small_state)
    assert extra["epoch"] == 2
    mgr.close()


def test_async_driver_run_resumes(tmp_path):
    """The pretrain driver with checkpoint_async=True survives a full
    train() + auto-resume cycle."""
    import dataclasses

    from moco_tpu.data.datasets import SyntheticDataset
    from moco_tpu.train import train
    from moco_tpu.utils.config import DataConfig, MocoConfig, OptimConfig, TrainConfig

    config = TrainConfig(
        moco=MocoConfig(
            arch="resnet18", dim=16, num_negatives=32, mlp=True,
            shuffle="none", cifar_stem=True, compute_dtype="float32",
        ),
        optim=OptimConfig(lr=0.03, epochs=1, cos=True),
        data=DataConfig(dataset="synthetic", image_size=16, global_batch=16, num_workers=2),
        workdir=str(tmp_path / "pre_async"),
        log_every=100,
        checkpoint_async=True,
    )
    dataset = SyntheticDataset(num_examples=32, image_size=16)
    train(config, dataset=dataset)
    # second run resumes from the async-written checkpoint
    config2 = dataclasses.replace(
        config, optim=dataclasses.replace(config.optim, epochs=2)
    )
    out = train(config2, dataset=dataset)
    assert out["epoch"] == 1
