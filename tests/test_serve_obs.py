"""Request-scoped serving observability (ISSUE 10): reqtrace stamps +
waterfalls, SLO burn-rate math, the flight recorder, batcher latency
accounting under saturation, the slow@ fault grammar, Prometheus
histogram export with exemplars, schema validators, serve-replica
trace merging, the obs_report Serving section, and the end-to-end
chaos capture (injected slow stage -> burn alert -> attributed flight
dump)."""

import json
import os
import threading
import time
import urllib.request

import numpy as np
import pytest

from moco_tpu.obs.flight import FlightRecorder, read_flight_dumps
from moco_tpu.obs.reqtrace import RequestIdAllocator, RequestTrace
from moco_tpu.obs.slo import SLOBurnTracker, serve_alert_spec
from moco_tpu.serve.batcher import ContinuousBatcher
from moco_tpu.utils import faults

from tests.conftest import load_script


# -- reqtrace ------------------------------------------------------------


def test_request_trace_waterfall_and_stage_sums():
    tr = RequestTrace("r0-000042", rows=3, replica=0)
    t0 = tr.t0
    tr.stamp("ingress", t0, t0 + 0.001)
    tr.stamp("queue_wait", t0 + 0.001, t0 + 0.011)
    tr.stamp("engine_execute", t0 + 0.011, t0 + 0.031)
    tr.stamp("engine_execute", t0 + 0.031, t0 + 0.041)  # repeated: sums
    ms = tr.stage_ms()
    assert ms["queue_wait"] == pytest.approx(10.0, abs=1e-6)
    assert ms["engine_execute"] == pytest.approx(30.0, abs=1e-6)
    assert tr.total_ms() == pytest.approx(41.0, abs=1e-6)
    wf = tr.waterfall()
    assert wf["request_id"] == "r0-000042" and wf["rows"] == 3
    assert [s["stage"] for s in wf["stages"]] == [
        "ingress", "queue_wait", "engine_execute", "engine_execute",
    ]
    assert wf["stages"][1]["start_ms"] == pytest.approx(1.0, abs=1e-3)


def test_request_trace_backdated_ingress():
    """The HTTP handler builds the trace AFTER reading the body; t0
    backdates so the ingress stage never starts before the origin."""
    t_arrival = time.perf_counter()
    time.sleep(0.005)
    tr = RequestTrace("r1-000000", rows=1, replica=1, t0=t_arrival)
    tr.stamp("ingress", t_arrival, time.perf_counter())
    wf = tr.waterfall()
    assert wf["stages"][0]["start_ms"] == 0.0
    assert wf["stages"][0]["dur_ms"] >= 5.0


def test_request_ids_unique_and_replica_scoped():
    ids = RequestIdAllocator(replica=2)
    seen = []
    lock = threading.Lock()

    def grab():
        got = [ids.new_trace().req_id for _ in range(200)]
        with lock:
            seen.extend(got)

    threads = [threading.Thread(target=grab) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(set(seen)) == 800
    assert all(r.startswith("r2-") for r in seen)


# -- SLO burn rate -------------------------------------------------------


def test_burn_rate_math_multi_window():
    t = SLOBurnTracker(slo_ms=100, objective=0.9, windows=(10, 100))
    # 20 requests over 2s: every 4th violates -> bad fraction 0.25
    for i in range(20):
        t.record(i % 4 != 0, now=1000.0 + i * 0.1)
    rates = t.burn_rates(now=1002.0)
    assert rates[10] == pytest.approx(0.25 / 0.1)
    assert rates[100] == pytest.approx(0.25 / 0.1)


def test_burn_rate_window_eviction_and_empty():
    t = SLOBurnTracker(slo_ms=100, objective=0.99, windows=(10,))
    assert t.burn_rates(now=0.0) == {10: None}  # silent service: no burn
    for i in range(10):
        t.record(False, now=100.0 + i)  # all violations
    assert t.burn_rates(now=109.0)[10] == pytest.approx(1.0 / 0.01)
    # 200s later every bucket aged out of the window
    assert t.burn_rates(now=300.0) == {10: None}
    payload = t.payload(now=109.0)
    assert payload["serve/slo_objective"] == 0.99
    assert payload["serve/burn_rate_10s"] == pytest.approx(100.0)


def test_burn_tracker_rejects_bad_config():
    with pytest.raises(ValueError):
        SLOBurnTracker(100, objective=1.0)
    with pytest.raises(ValueError):
        SLOBurnTracker(100, windows=())
    with pytest.raises(ValueError):
        SLOBurnTracker(100, windows=(10, 10))


def test_serve_alert_spec_parses_and_tightens():
    from moco_tpu.obs.alerts import parse_rules

    rules = parse_rules(serve_alert_spec(250.0, windows=(30, 300)))
    by_name = {r.name: r for r in rules}
    assert by_name["slo_burn_fast"].field == "serve/burn_rate_30s"
    assert by_name["slo_burn_slow"].field == "serve/burn_rate_300s"
    assert by_name["slo_p99_over"].value == 250.0
    # without an slo the p99 rule drops out
    assert "slo_p99_over" not in {
        r.name for r in parse_rules(serve_alert_spec(None))
    }


def test_alert_engine_on_fire_hook(tmp_path):
    from moco_tpu.obs.alerts import AlertEngine, parse_rules

    fired = []
    eng = AlertEngine(
        parse_rules("threshold@name=hot:field=x:value=1"),
        workdir=str(tmp_path),
        on_fire=fired.append,
    )
    eng.observe(1, {"x": 0.5})
    assert not fired
    eng.observe(2, {"x": 2.0})
    assert [a["rule"] for a in fired] == ["hot"]
    eng.close()


# -- flight recorder -----------------------------------------------------


def _wf(rid, total_ms, stage="engine_execute"):
    return {
        "request_id": rid,
        "replica": 0,
        "rows": 1,
        "wall_t0": 0.0,
        "total_ms": total_ms,
        "stages": [{"stage": stage, "start_ms": 0.0, "dur_ms": total_ms}],
    }


def test_flight_recorder_ring_bounds_and_slowest(tmp_path):
    fr = FlightRecorder(max_requests=4, max_metrics=2)
    for i in range(10):
        fr.record_request(_wf(f"r0-{i:06d}", float(i)))
    fr.record_metrics(1, {"serve/qps": 1.0})
    fr.record_metrics(2, {"serve/qps": 2.0})
    fr.record_metrics(3, {"serve/qps": 3.0})
    snap = fr.snapshot(top_n=2)
    assert snap["requests_recorded"] == 4  # ring evicted the rest
    assert [r["request_id"] for r in snap["slowest"]] == ["r0-000009", "r0-000008"]
    assert [m["serve/qps"] for m in snap["metrics"]] == [2.0, 3.0]
    path = fr.dump(str(tmp_path), reason="test", extra={"k": 1})
    assert os.path.basename(path).startswith("flight_")
    rec = json.load(open(path))
    assert rec["reason"] == "test" and rec["k"] == 1
    assert len(rec["requests"]) == 4
    # two dumps in the same second stay distinct files
    path2 = fr.dump(str(tmp_path), reason="again")
    assert path2 != path
    loaded = read_flight_dumps(str(tmp_path))
    assert [os.path.basename(p) for p, _ in loaded] == sorted(
        os.path.basename(p) for p, _ in loaded
    )
    assert loaded[-1][1]["reason"] == "again"


# -- batcher latency accounting (the ISSUE-10 satellite) ----------------


def _echo(images, wn, *, stages=None, engine_s=0.0):
    if engine_s:
        t0 = time.perf_counter()
        time.sleep(engine_s)
        if stages is not None:
            stages["engine_execute"] = (
                stages.get("engine_execute", 0.0) + time.perf_counter() - t0
            )
    emb = np.arange(images.shape[0], dtype=np.float32)[:, None]
    return {"embedding": emb}, [(images.shape[0], images.shape[0])]


def test_latency_accounting_sums_to_wall_under_saturation():
    """Every request's stage durations must sum to within eps of its
    measured wall latency, and under saturation with a slowed engine
    the queue_wait stage must dominate."""
    engine_s = 0.05

    def run_batch(images, wn, *, stages=None):
        return _echo(images, wn, stages=stages, engine_s=engine_s)

    b = ContinuousBatcher(run_batch, max_batch=4, slo_ms=10_000, reqtrace=True)
    try:
        # a burst of 2-row requests: max_batch 4 -> 2 requests/flush,
        # 10 serial flushes at ~50ms each; later requests queue behind
        # earlier flushes, so queue_wait accumulates
        futs = [b.submit(np.zeros((2, 4, 4, 3), np.uint8)) for _ in range(20)]
        for f in futs:
            f.result(30)
        total_queue = total_engine = 0.0
        for f in futs:
            assert f.trace is not None
            lat_ms = f.latency_s * 1e3
            stage_ms = f.trace.stage_ms()
            ssum = sum(stage_ms.values())
            # eps: scheduling gaps between dequeue and flush / between
            # run end and scatter — small next to a 50ms engine stage
            assert abs(ssum - lat_ms) <= max(0.15 * lat_ms, 25.0), (
                f"{f.trace.req_id}: stages {ssum:.1f}ms vs wall {lat_ms:.1f}ms "
                f"({stage_ms})"
            )
            total_queue += stage_ms.get("queue_wait", 0.0)
            total_engine += stage_ms.get("engine_execute", 0.0)
        # saturation: waiting for earlier flushes dwarfs own execution
        assert total_queue > 2.0 * total_engine, (total_queue, total_engine)
    finally:
        b.close()


def test_batcher_stage_split_lands_in_metrics_payload():
    def run_batch(images, wn, *, stages=None):
        return _echo(images, wn, stages=stages, engine_s=0.01)

    b = ContinuousBatcher(run_batch, max_batch=8, slo_ms=1000, reqtrace=True)
    try:
        b.submit(np.zeros((8, 4, 4, 3), np.uint8)).result(10)
        p = b.metrics.payload()
        assert p["serve/trace_requests"] == 1
        assert p["serve/trace_engine_execute_ms"] >= 10.0
        assert p["serve/trace_queue_wait_ms"] >= 0.0
        assert p["serve/p99_exemplar"].startswith("r0-")
        assert p["serve/p99_exemplar_ms"] > 0
        # the window resets: a second payload with no traffic carries no
        # stage means and a null exemplar
        p2 = b.metrics.payload()
        assert "serve/trace_engine_execute_ms" not in p2
        assert p2["serve/p99_exemplar"] is None
    finally:
        b.close()


def test_batcher_tracing_off_is_traceless():
    b = ContinuousBatcher(_echo, max_batch=4, slo_ms=1000)  # reqtrace off
    try:
        fut = b.submit(np.zeros((1, 4, 4, 3), np.uint8))
        fut.result(10)
        assert fut.trace is None
        p = b.metrics.payload()
        assert p["serve/p99_exemplar"] is None
        assert not any(k.startswith("serve/trace_") for k in p)
        # the latency histogram still counts (it needs no per-request id)
        assert p["serve/latency_hist"]["count"] == 1
    finally:
        b.close()


def test_batcher_modes_and_stages_contracts_coexist():
    """A 3-positional-arg callable gets modes; the keyword-only stages
    param must NOT be mistaken for the modes contract (and vice versa)."""
    seen = {}

    def three_arg(images, wn, modes, *, stages=None):
        seen["modes"] = modes
        seen["stages_passed"] = stages is not None
        return _echo(images, wn)

    b = ContinuousBatcher(three_arg, max_batch=2, slo_ms=500, reqtrace=True)
    try:
        b.submit(
            np.zeros((2, 4, 4, 3), np.uint8), want_neighbors=True, mode="ivf"
        ).result(10)
        assert seen["modes"] == ("ivf",)
        assert seen["stages_passed"] is True
    finally:
        b.close()

    def keyword_stages_only(images, wn, *, stages=None):
        seen["kw_only"] = True
        assert not isinstance(stages, tuple)  # never the modes tuple
        return _echo(images, wn)

    b2 = ContinuousBatcher(keyword_stages_only, max_batch=2, slo_ms=500, reqtrace=True)
    try:
        b2.submit(np.zeros((1, 4, 4, 3), np.uint8)).result(10)
        assert seen["kw_only"]
    finally:
        b2.close()


# -- slow@ fault grammar -------------------------------------------------


def test_slow_fault_grammar_parses():
    plan = faults.FaultPlan("slow@site=serve.engine_execute:ms=250:at=2:times=3")
    assert plan.describe() == [
        ("slow", {"site": "serve.engine_execute", "ms": 250.0, "at": 2, "times": 3})
    ]
    with pytest.raises(ValueError):
        faults.FaultPlan("slow@site=x:bogus=1")


def test_slow_fault_fires_at_the_right_calls():
    faults.install("slow@site=serve.test_stage:ms=40:at=2:times=2")
    try:
        durs = []
        for _ in range(4):
            t0 = time.perf_counter()
            faults.maybe_slow("serve.test_stage")
            durs.append(time.perf_counter() - t0)
        assert durs[0] < 0.02  # call 1: clean
        assert durs[1] >= 0.04 and durs[2] >= 0.04  # calls 2-3: slowed
        assert durs[3] < 0.02  # call 4: clean again
        # other sites never sleep
        t0 = time.perf_counter()
        faults.maybe_slow("serve.other")
        assert time.perf_counter() - t0 < 0.02
    finally:
        faults.clear()


# -- Prometheus histogram + exemplar ------------------------------------


def test_prometheus_renders_cumulative_histogram_with_exemplar():
    from moco_tpu.obs.sinks import PrometheusSink

    sink = PrometheusSink(port=0)
    try:
        sink.write(1, {
            "serve/qps": 5.0,
            "serve/latency_hist": {
                "le": [10.0, 100.0, 1000.0],
                "counts": [3, 2, 1, 1],  # per-bucket; +Inf slot last
                "sum": 1500.0,
                "count": 7,
                "exemplar": {"request_id": "r0-000007", "latency_ms": 42.0},
            },
        })
        body = sink.render()
        assert "# TYPE moco_serve_latency_ms histogram" in body
        assert 'moco_serve_latency_ms_bucket{le="10"} 3' in body
        # cumulative counts, exemplar attached to the bucket it falls in
        assert (
            'moco_serve_latency_ms_bucket{le="100"} 5 '
            '# {request_id="r0-000007"} 42' in body
        )
        assert 'moco_serve_latency_ms_bucket{le="1000"} 6' in body
        assert 'moco_serve_latency_ms_bucket{le="+Inf"} 7' in body
        assert "moco_serve_latency_ms_sum 1500.0" in body
        assert "moco_serve_latency_ms_count 7" in body
        assert "moco_serve_qps 5.0" in body  # gauges still render
        # a scrape parses: every non-comment line is "name{...} value"
        for line in body.strip().splitlines():
            if line.startswith("#"):
                continue
            name_part = line.split(" # ")[0]
            assert len(name_part.rsplit(" ", 1)) == 2, line
    finally:
        sink.close()


# -- schema --------------------------------------------------------------


def test_schema_validates_new_serve_fields():
    from moco_tpu.obs import schema

    good = {
        "step": 1,
        "time": 0.0,
        "serve/burn_rate_60s": 2.5,
        "serve/burn_rate_600s": None,
        "serve/slo_objective": 0.99,
        "serve/trace_engine_execute_ms": 12.5,
        "serve/trace_requests": 4,
        "serve/p99_exemplar": "r0-000123",
        "serve/p99_exemplar_ms": 812.0,
        "serve/latency_hist": {
            "le": [1.0, 10.0],
            "counts": [1, 2, 0],
            "sum": 21.0,
            "count": 3,
        },
    }
    assert schema.validate_line(good) == []
    # exemplar is a string INSIDE the numeric serve/ family: the
    # explicit validator must win over the prefix check
    bad_exemplar = dict(good, **{"serve/p99_exemplar": 17})
    assert schema.validate_line(bad_exemplar)
    # burn rates: longest-prefix validator (non-negative) shadows serve/
    bad_burn = dict(good, **{"serve/burn_rate_60s": -1.0})
    assert schema.validate_line(bad_burn)
    bad_stage = dict(good, **{"serve/trace_scatter_ms": -0.1})
    assert schema.validate_line(bad_stage)
    for mutilation in (
        {"le": [10.0, 1.0], "counts": [1, 1, 1], "sum": 1.0, "count": 3},  # unsorted
        {"le": [1.0], "counts": [1], "sum": 1.0, "count": 1},  # missing +Inf slot
        {"le": [1.0], "counts": [1, -1], "sum": 1.0, "count": 0},  # negative
        "nope",
    ):
        assert schema.validate_line(
            dict(good, **{"serve/latency_hist": mutilation})
        ), mutilation


# -- trace merge: serving replicas join the timeline --------------------


def test_trace_merge_aligns_serve_replica_tracks(tmp_path):
    tm = load_script("trace_merge.py")
    wd = str(tmp_path)
    # training process 0: anchor at wall 1000.0
    with open(os.path.join(wd, "trace_events.jsonl"), "w") as f:
        f.write(json.dumps({"name": "step", "ts": 0.0, "dur": 5.0, "tid": 1,
                            "thread": "main", "p": 0}) + "\n")
    with open(os.path.join(wd, "heartbeat.p0.json"), "w") as f:
        json.dump({"process": 0, "host": "trainhost", "time": 1000.0,
                   "trace_wall_t0": 1000.0}, f)
    # serve replica 1: started 2.5s later; request span on a lane
    with open(os.path.join(wd, "trace_events.s1.jsonl"), "w") as f:
        f.write(json.dumps({"name": "request", "ts": 10.0, "dur": 3.0, "tid": 1,
                            "thread": "requests-0", "p": 1,
                            "args": {"request_id": "r1-000000"}}) + "\n")
    with open(os.path.join(wd, "heartbeat.s1.json"), "w") as f:
        json.dump({"process": 1, "role": "serve", "host": "servehost",
                   "time": 1002.5, "trace_wall_t0": 1002.5}, f)
    out = os.path.join(wd, "merged.json")
    summary = tm.merge_traces(wd, out)
    assert summary["serve_replicas"][1]["offset_us"] == pytest.approx(2.5e6)
    merged = json.load(open(out))
    by_pid = {}
    for ev in merged["traceEvents"]:
        by_pid.setdefault(ev["pid"], []).append(ev)
    assert 0 in by_pid and tm.SERVE_PID_BASE + 1 in by_pid
    req = next(e for e in by_pid[tm.SERVE_PID_BASE + 1] if e.get("ph") == "X")
    assert req["ts"] == pytest.approx(2.5e6 + 10.0)  # clock-aligned
    name_meta = next(
        e for e in by_pid[tm.SERVE_PID_BASE + 1] if e.get("ph") == "M"
        and e["name"] == "process_name"
    )
    assert "serve replica 1" in name_meta["args"]["name"]
    assert merged["otherData"]["serve_replicas"] == [1]


# -- obs_report: the Serving section ------------------------------------


def test_obs_report_serving_section(tmp_path):
    rep = load_script("obs_report.py")
    wd = str(tmp_path)
    lines = []
    for i in range(6):
        lines.append({
            "step": i + 1, "time": 100.0 + i,
            "serve/qps": 10.0 + i, "serve/p99_ms": 90.0 + i,
            "serve/p50_ms": 40.0, "serve/requests": 10 * (i + 1),
            "serve/slo_ms": 100.0, "serve/slo_objective": 0.99,
            "serve/slo_violations": i,
            "serve/burn_rate_60s": 0.5 * i,
            "serve/trace_queue_wait_ms": 30.0,
            "serve/trace_engine_execute_ms": 55.0,
            "serve/trace_scatter_ms": 5.0,
            "serve/p99_exemplar": f"r0-{i:06d}",
        })
    with open(os.path.join(wd, "metrics.jsonl"), "w") as f:
        for rec in lines:
            f.write(json.dumps(rec) + "\n")
    fr = FlightRecorder()
    fr.record_request(_wf("r0-000005", 500.0))
    fr.dump(wd, reason="alert:slo_burn_fast")
    report = rep.render_report(
        os.path.join(wd, "metrics.jsonl"), workdir=wd
    )
    assert "## Serving" in report
    assert "stage waterfall" in report
    assert "engine_execute" in report
    assert "serve/burn_rate_60s" in report
    assert "r0-000005" in report  # slowest request from the flight dump
    assert "p99 exemplar" in report


def test_obs_report_fleet_tracing_section(tmp_path):
    rep = load_script("obs_report.py")
    wd = str(tmp_path)
    lines = []
    for i in range(4):
        lines.append({
            "step": i + 1, "time": 100.0 + i,
            "fleet_serve/requests": 20 * (i + 1),
            "fleet_serve/slo_ms": 1000.0, "fleet_serve/p99_ms": 400.0,
            "fleet_serve/hedges": 6, "fleet_serve/hedge_wins": 3,
            "fleet_serve/hedge_wasted_ms": 1234.5,
            "fleet_serve/retries": 2,
            "fleet_serve/critpath_router_admission_ms": 1.0,
            "fleet_serve/critpath_net_send_ms": 4.0,
            "fleet_serve/critpath_replica_engine_execute_ms": 80.0,
            "fleet_serve/critpath_retry_failed_ms": 12.0,
            "fleet_serve/critpath_router_other_ms": 3.0,
        })
    with open(os.path.join(wd, "metrics.jsonl"), "w") as f:
        for rec in lines:
            f.write(json.dumps(rec) + "\n")
    # a router flight dump with one stitched multi-hop waterfall
    fr = FlightRecorder()
    fr.record_request({
        "trace_id": "ab" * 16, "request_id": "r2-000009", "status": 200,
        "total_ms": 950.0,
        "attempts": [{"outcome": "failed"}, {"outcome": "ok", "winner": True}],
        "stages": [
            {"stage": "router_admission", "start_ms": 0.0, "dur_ms": 1.0},
            {"stage": "replica_engine_execute", "start_ms": 10.0, "dur_ms": 900.0},
        ],
    })
    fr.dump(wd, reason="alert:slo_burn_fast", extra={"role": "router"})
    report = rep.render_report(os.path.join(wd, "metrics.jsonl"), workdir=wd)
    assert "## Fleet tracing" in report
    assert "critical path" in report
    assert "replica_engine_execute" in report
    assert "win rate 50%" in report
    assert "retries: 2" in report
    assert "slowest distributed waterfalls" in report
    assert "ab" * 16 in report and "r2-000009" in report
    # the router dump must NOT leak into the per-replica Serving section
    assert "slowest requests (flight recorder" not in report


# -- end-to-end chaos: slow stage -> burn alert -> attributed dump ------


class _TinyEngine:
    """Engine-shaped stub with the REAL fault hook discipline: the
    injected slow@serve.engine_execute sleep happens inside the stage's
    own timing window, like InferenceEngine._run_bucket."""

    buckets = (1, 4)
    recompiles_after_warmup = 0
    num_features = 4
    image_size = 4

    def warmup(self):
        pass

    def embed(self, images, stages=None):
        t0 = time.perf_counter()
        faults.maybe_slow("serve.engine_execute")
        emb = np.ones((images.shape[0], 4), np.float32) / 2.0
        if stages is not None:
            stages["engine_execute"] = (
                stages.get("engine_execute", 0.0) + time.perf_counter() - t0
            )
        return emb, [(images.shape[0], images.shape[0])]


def test_server_chaos_flight_capture(tmp_path):
    """The serve_smoke SLO leg's story at unit scale: an injected
    slow@serve.engine_execute request trips the burn-rate alert and the
    flight dump attributes its tail to exactly that stage."""
    from moco_tpu.obs import schema
    from moco_tpu.obs.sinks import JsonlSink
    from moco_tpu.serve.server import ServeServer

    wd = str(tmp_path)
    sink = JsonlSink(wd)
    server = ServeServer(
        _TinyEngine(), index=None, port=0, slo_ms=100.0,
        sink=sink, metrics_flush_s=0.1, workdir=wd,
        slo_objective=0.9, burn_windows=(10, 60),
        alert_spec="threshold@name=slo_burn_fast:field=serve/burn_rate_10s:value=1.0",
    )
    imgs = np.zeros((2, 4, 4, 3), np.uint8)

    def post(path="/embed"):
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}{path}", data=imgs.tobytes(),
            headers={"X-Image-Shape": "2,4,4,3"},
        )
        with urllib.request.urlopen(req, timeout=30) as r:
            return json.loads(r.read())

    try:
        for _ in range(10):
            post()
        faults.install("slow@site=serve.engine_execute:ms=400:at=1:times=2")
        try:
            slowed = [post()["request_id"] for _ in range(2)]
        finally:
            faults.clear()
        for _ in range(4):
            post()
        deadline = time.time() + 8.0
        while time.time() < deadline and not read_flight_dumps(wd):
            time.sleep(0.05)
        with urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/debug/flight", timeout=10
        ) as r:
            debug = json.loads(r.read())
    finally:
        server.close()
        sink.close()
    from moco_tpu.obs.alerts import read_alerts

    alerts = read_alerts(os.path.join(wd, "alerts.jsonl"))
    assert any(a["rule"] == "slo_burn_fast" for a in alerts), alerts
    dumps = read_flight_dumps(wd)
    assert dumps, "alert fired but no flight dump landed"
    alert_dump = next(
        rec for _, rec in dumps if str(rec.get("reason", "")).startswith("alert:")
    )
    dumped = {r["request_id"]: r for r in alert_dump["requests"]}
    assert slowed[0] in dumped
    stage_ms = {s["stage"]: s["dur_ms"] for s in dumped[slowed[0]]["stages"]}
    assert max(stage_ms, key=stage_ms.get) == "engine_execute"
    assert stage_ms["engine_execute"] >= 400.0
    # the on-demand endpoint dumped too, and holds both offenders
    assert debug["dump_path"]
    debug_ids = {r["request_id"] for r in debug["requests"]}
    assert set(slowed) <= debug_ids
    # the metrics stream is schema-strict with the whole new surface on it
    errors = schema.validate_file(os.path.join(wd, "metrics.jsonl"))
    assert not errors, errors[:5]
    lines = schema.read_metrics(os.path.join(wd, "metrics.jsonl"))
    assert any(r.get("serve/burn_rate_10s") is not None for r in lines)
    assert any(r.get("serve/p99_exemplar") in slowed for r in lines)
    assert any(r.get("event") == "alert" for r in lines)
    # request spans + the clock anchor reached the replica stream
    spans = [json.loads(l) for l in open(os.path.join(wd, "trace_events.s0.jsonl"))]
    names = {s["name"] for s in spans}
    assert {"request", "req/engine_execute", "req/queue_wait"} <= names
    anchor = json.load(open(os.path.join(wd, "heartbeat.s0.json")))
    assert anchor["role"] == "serve" and "trace_wall_t0" in anchor


# -- perf ledger: the trace-overhead cap --------------------------------


def test_perf_ledger_gates_trace_overhead(tmp_path):
    pl = load_script("perf_ledger.py")
    ledger = str(tmp_path / "ledger.json")
    rec = {
        "metric": "moco_v1_r18_cpu_smoke_imgs_per_sec",
        "value": 10.0,
        "serving": {
            "metric": "moco_serve_resnet18_cpu_smoke_queries_per_sec",
            "value": 8.0,
            "trace_overhead_pct": 3.0,
        },
    }
    cand = str(tmp_path / "bench.json")
    with open(cand, "w") as f:
        json.dump(rec, f)
    pl.append(ledger, cand, "t01")
    assert pl.check(ledger, cand) == 0  # under the cap
    bad = dict(rec, serving=dict(rec["serving"], trace_overhead_pct=60.0))
    with open(cand, "w") as f:
        json.dump(bad, f)
    assert pl.check(ledger, cand) == 1  # cpu cap is 25%
    # an accelerator serving record gates at the tight 5%
    accel = {
        "metric": "moco_v1_r50_imgs_per_sec_per_chip",
        "value": 100.0,
        "serving": {
            "metric": "moco_serve_resnet50_queries_per_sec_per_chip",
            "value": 50.0,
            "trace_overhead_pct": 7.0,
        },
    }
    with open(cand, "w") as f:
        json.dump(accel, f)
    assert pl.check(ledger, cand) == 1
    # a record with no overhead field (old bench) still checks cleanly
    legacy = dict(rec, serving={k: v for k, v in rec["serving"].items()
                                if k != "trace_overhead_pct"})
    with open(cand, "w") as f:
        json.dump(legacy, f)
    assert pl.check(ledger, cand) == 0
    # the router-side distributed-tracing A/B (ISSUE 18) gates under the
    # same caps as the replica-side series
    routed = dict(rec, serving=dict(
        rec["serving"], router_trace_overhead_pct=3.0
    ))
    with open(cand, "w") as f:
        json.dump(routed, f)
    assert pl.check(ledger, cand) == 0
    routed_bad = dict(rec, serving=dict(
        rec["serving"], router_trace_overhead_pct=60.0
    ))
    with open(cand, "w") as f:
        json.dump(routed_bad, f)
    assert pl.check(ledger, cand) == 1
