"""Device prefetch ring (ISSUE 5 tentpole): correctness vs the sync
path, real overlap, donation safety, clean shutdown.

The overlap assertions use the deterministic `delay@site=...` fault
hooks (utils/faults.py) to slow individual stages — wall-clock math on
injected, known stage times instead of flaky scheduler-dependent
measurements.
"""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from moco_tpu.data.device_prefetch import DevicePrefetchRing, H2D_SITE
from moco_tpu.data.pipeline import TwoCropPipeline, _prefetch
from moco_tpu.parallel import create_mesh
from moco_tpu.utils import faults
from moco_tpu.utils.config import DataConfig


@pytest.fixture(autouse=True)
def _no_fault_plan():
    faults.clear()
    yield
    faults.clear()


@pytest.fixture(scope="module")
def folder(tmp_path_factory):
    """Tiny JPEG ImageFolder — the jpeg/cache pipeline variants decode
    from it; geometry varies per image so host-RRC boxes are exercised
    against original dims."""
    from PIL import Image as PILImage

    root = tmp_path_factory.mktemp("ring_imgs")
    rng = np.random.default_rng(0)
    for cls in ("a", "b"):
        (root / cls).mkdir()
        for i in range(16):
            h, w = rng.integers(40, 90, 2)
            arr = rng.integers(0, 256, (h, w, 3), dtype=np.uint8)
            PILImage.fromarray(arr).save(root / cls / f"i{i}.jpg", quality=92)
    return str(root)


def _variant_config(variant: str, folder: str, tmp_path) -> DataConfig:
    """The three input modes the ring must feed identically: JPEG decode
    + host RRC, packed-RGB cache + host RRC, canvas (device-side crop)."""
    if variant == "jpeg":
        return DataConfig(
            dataset="imagefolder", data_dir=folder, image_size=16,
            global_batch=8, num_workers=2, host_rrc=True,
        )
    if variant == "cache":
        return DataConfig(
            dataset="imagefolder", data_dir=folder, image_size=16,
            global_batch=8, num_workers=2, host_rrc=True,
            cache_dir=str(tmp_path / "rgb_cache"),
        )
    assert variant == "canvas"
    return DataConfig(
        dataset="imagefolder", data_dir=folder, image_size=16,
        global_batch=8, num_workers=2, host_rrc=False,
    )


class TestRingMatchesSyncPath:
    @pytest.mark.parametrize("variant", ["jpeg", "cache", "canvas"])
    def test_bit_identical_batches(self, variant, folder, tmp_path):
        mesh = create_mesh()
        cfg = _variant_config(variant, folder, tmp_path)
        pipe = TwoCropPipeline(cfg, mesh, seed=0)
        sync = list(pipe.epoch(0))
        ring = list(pipe.epoch(0, device=True))
        assert len(sync) == len(ring) == pipe.steps_per_epoch
        for a, b in zip(sync, ring):
            np.testing.assert_array_equal(np.asarray(a["im_q"]), np.asarray(b["im_q"]))
            np.testing.assert_array_equal(np.asarray(a["im_k"]), np.asarray(b["im_k"]))

    def test_synthetic_variant_and_sharding(self):
        mesh = create_mesh()
        cfg = DataConfig(dataset="synthetic", image_size=16, global_batch=16, num_workers=2)
        pipe = TwoCropPipeline(cfg, mesh, seed=0)
        sync_it = pipe.epoch(0)
        a = next(sync_it)
        sync_it.close()
        it = pipe.epoch(0, device=True)
        b = next(iter(it))
        np.testing.assert_array_equal(np.asarray(a["im_q"]), np.asarray(b["im_q"]))
        # ring batches keep the data-axis sharding the step expects
        assert len(b["im_q"].addressable_shards) == jax.device_count()
        it.close()

    def test_labeled_pipeline_ring(self, folder, tmp_path):
        from moco_tpu.data.pipeline import LabeledPipeline

        mesh = create_mesh()
        cfg = _variant_config("jpeg", folder, tmp_path)
        pipe = LabeledPipeline(cfg, mesh, seed=0)
        sync_it, ring_it = pipe.epoch(0), pipe.epoch(0, device=True)
        (xs, ys) = next(sync_it)
        (xr, yr) = next(iter(ring_it))
        sync_it.close()
        ring_it.close()
        np.testing.assert_array_equal(np.asarray(xs), np.asarray(xr))
        np.testing.assert_array_equal(np.asarray(ys), np.asarray(yr))


class TestOverlap:
    def test_wall_clock_overlaps_stages(self):
        """With an injected slow wire (0.05 s/batch) AND slow decode
        (0.05 s/batch), the overlapped wall for N batches must be well
        under the serial sum — the stages run concurrently. The sync
        path by construction pays decode+wire serially on its one
        producer thread."""
        mesh = create_mesh()
        cfg = DataConfig(dataset="synthetic", image_size=8, global_batch=8, num_workers=2)
        pipe = TwoCropPipeline(cfg, mesh, seed=0)
        n = 8
        delay = 0.05
        faults.install(
            f"delay@site=data.read:seconds={delay},"
            f"delay@site={H2D_SITE}:seconds={delay}"
        )
        it = pipe.epoch(0, device=True, depth=2)
        # consume n batches; time from first to last so thread spin-up
        # is excluded
        next(it)
        t0 = time.perf_counter()
        for _ in range(n):
            next(it)
        wall = time.perf_counter() - t0
        it.close()
        serial = 2 * delay * n  # decode + wire, if they took turns
        assert wall < 0.8 * serial, (
            f"no overlap: wall {wall:.3f}s vs serial bound {serial:.3f}s"
        )
        # ...and the per-batch wire time was actually recorded
        pay = it.stats_payload()
        assert pay["t_transfer"] >= delay
        assert pay["transfer_bytes"] > 0
        assert 0 <= pay["prefetch_depth_live"] <= 2

    def test_sync_path_is_serial_baseline(self):
        """Control for the assertion above: the same injected delays on
        the SYNC path cost the full serial sum per batch."""
        mesh = create_mesh()
        cfg = DataConfig(dataset="synthetic", image_size=8, global_batch=8, num_workers=2)
        pipe = TwoCropPipeline(cfg, mesh, seed=0)
        n, delay = 4, 0.05
        faults.install(f"delay@site=data.read:seconds={delay}")
        it = pipe.epoch(0)
        next(it)
        t0 = time.perf_counter()
        for _ in range(n):
            next(it)
        wall = time.perf_counter() - t0
        it.close()
        assert wall >= 0.9 * delay * n


class TestDonation:
    def test_donated_slots_match_plain(self):
        """prefetch_donate recycles the consumed staging buffer; outputs
        must be identical and no donated buffer may be touched again
        (jax raises on donated-buffer reuse when it is)."""
        mesh = create_mesh()
        cfg = DataConfig(dataset="synthetic", image_size=16, global_batch=16, num_workers=2)
        pipe = TwoCropPipeline(cfg, mesh, seed=0)
        plain_it = pipe.epoch(0)
        plain = [next(plain_it)]
        plain_it.close()
        don_it = pipe.epoch(0, device=True, donate=True)
        donated = []
        for _ in range(3):
            donated.append(next(don_it))
        don_it.close()
        np.testing.assert_array_equal(
            np.asarray(plain[0]["im_q"]), np.asarray(donated[0]["im_q"])
        )
        # every ring batch stays fully readable after later transfers
        # rotated (and donated) other slots
        for b in donated:
            assert bool(jnp.isfinite(b["im_q"]).all())
            assert bool(jnp.isfinite(b["im_k"]).all())


def _pipeline_threads():
    """Live prefetch-producer / transfer-ring threads (the leak
    targets; the pipeline's decode POOL threads are lazy-spawned and
    live for the pipeline's lifetime by design, so absolute
    active_count comparisons are noise)."""
    return [
        t for t in threading.enumerate()
        if t.name.startswith(("prefetch", "device_prefetch")) and t.is_alive()
    ]


def _assert_pipeline_threads_exit(timeout: float = 5.0):
    deadline = time.time() + timeout
    while _pipeline_threads() and time.time() < deadline:
        time.sleep(0.02)
    leaked = _pipeline_threads()
    assert not leaked, f"leaked threads: {[t.name for t in leaked]}"


class TestShutdown:
    def test_close_mid_epoch_leaks_no_threads(self):
        """The PR-1..4 era leak: abandoning the iterator mid-epoch left
        the daemon producer blocked on q.put forever. close() must end
        both the producer and the transfer thread."""
        mesh = create_mesh()
        cfg = DataConfig(dataset="synthetic", image_size=8, global_batch=8, num_workers=2)
        pipe = TwoCropPipeline(cfg, mesh, seed=0)
        it = pipe.epoch(0, device=True)
        next(it)  # producer + ring threads are live and mid-stream
        assert _pipeline_threads()
        it.close()
        _assert_pipeline_threads_exit()

    def test_close_unblocks_put_blocked_producer(self):
        """Producer blocked on a FULL queue (consumer never drains — the
        exact leak shape: an exception in the step loop) must exit."""
        mesh = create_mesh()
        cfg = DataConfig(dataset="synthetic", image_size=8, global_batch=8, num_workers=2)
        pipe = TwoCropPipeline(cfg, mesh, seed=0)
        it = pipe.epoch(0, device=True, depth=1)
        # never consume: both queues fill, both threads block on put
        time.sleep(0.3)
        assert _pipeline_threads()
        it.close()
        _assert_pipeline_threads_exit()

    def test_sync_iterator_close_is_also_leakfree(self):
        mesh = create_mesh()
        cfg = DataConfig(dataset="synthetic", image_size=8, global_batch=8, num_workers=2)
        pipe = TwoCropPipeline(cfg, mesh, seed=0)
        it = pipe.epoch(0)
        next(it)
        it.close()
        _assert_pipeline_threads_exit()

    def test_abandoned_iterator_self_cleans_on_gc(self):
        """A consumer that simply DROPS the iterator (no close()) must
        not leak threads either: the producer/ring threads hold no
        reference to the iterator object, so GC fires __del__, which
        flips the stop flag and lets them unwind."""
        import gc

        mesh = create_mesh()
        cfg = DataConfig(dataset="synthetic", image_size=8, global_batch=8, num_workers=2)
        pipe = TwoCropPipeline(cfg, mesh, seed=0)
        next(iter(pipe.epoch(0, device=True)))  # abandoned immediately
        next(iter(pipe.epoch(0)))  # sync path too
        gc.collect()
        _assert_pipeline_threads_exit()

    def test_exhausted_iterator_is_reentrant_safe(self):
        """next() after exhaustion and close() after exhaustion both
        behave (no hang on an empty queue, no double-join error)."""
        mesh = create_mesh()
        cfg = DataConfig(dataset="synthetic", image_size=8, global_batch=64, num_workers=2)
        pipe = TwoCropPipeline(cfg, mesh, seed=0)
        it = pipe.epoch(0, device=True)
        batches = list(it)
        assert len(batches) == pipe.steps_per_epoch
        assert next(it, None) is None
        it.close()
        it.close()

    def test_producer_error_propagates_then_shuts_down(self, monkeypatch):
        """An injected decode IOError past the retry budget must surface
        at the consumer's next() (not vanish on the ring thread) and
        leave no live threads behind."""
        monkeypatch.setenv("MOCO_IO_RETRIES", "2")
        monkeypatch.setenv("MOCO_IO_RETRY_BASE", "0.01")
        mesh = create_mesh()
        cfg = DataConfig(dataset="synthetic", image_size=8, global_batch=8, num_workers=2)
        pipe = TwoCropPipeline(cfg, mesh, seed=0)
        # every read fails: retries exhaust, the error crosses both queues
        faults.install("io@site=data.read:at=1:times=999")
        it = pipe.epoch(0, device=True)
        with pytest.raises(IOError):
            for _ in range(pipe.steps_per_epoch):
                next(it)
        it.close()


class TestRingUnit:
    """DevicePrefetchRing against a hand-rolled transfer fn — no
    pipeline, exact control of item flow."""

    def test_order_and_stats(self):
        items = list(range(10))
        ring = DevicePrefetchRing(
            iter(items), lambda x: (x * 2, 100), depth=3
        )
        assert list(ring) == [x * 2 for x in items]
        assert ring.stats.batches == 10
        assert ring.stats.total_bytes == 1000
        assert ring.stats.wire_rate_bytes_per_sec() > 0

    def test_transfer_error_reraises(self):
        def boom(x):
            raise RuntimeError("wire down")

        ring = DevicePrefetchRing(iter([1]), boom, depth=2)
        with pytest.raises(RuntimeError, match="wire down"):
            next(ring)

    def test_depth_validation(self):
        with pytest.raises(ValueError, match="depth"):
            DevicePrefetchRing(iter([]), lambda x: (x, 0), depth=0)

    def test_empty_payload_before_first_batch(self):
        ring = DevicePrefetchRing(iter([]), lambda x: (x, 0), depth=1)
        assert list(ring) == []
        assert ring.stats_payload() == {}


def test_delay_fault_hook_grammar():
    """The delay@ fault kind: per-site, 1-based at/times window, every
    call by default."""
    plan = faults.install("delay@site=wire:seconds=0.02:at=2:times=2")
    t0 = time.perf_counter()
    plan.maybe_delay("wire")  # call 1: before `at` — no sleep
    fast = time.perf_counter() - t0
    t0 = time.perf_counter()
    plan.maybe_delay("wire")  # call 2: sleeps
    slow = time.perf_counter() - t0
    t0 = time.perf_counter()
    plan.maybe_delay("wire")  # call 3: sleeps (times=2)
    slow2 = time.perf_counter() - t0
    t0 = time.perf_counter()
    plan.maybe_delay("wire")  # call 4: window over
    fast2 = time.perf_counter() - t0
    assert fast < 0.01 and fast2 < 0.01
    assert slow >= 0.02 and slow2 >= 0.02
    # other sites unaffected
    t0 = time.perf_counter()
    plan.maybe_delay("elsewhere")
    assert time.perf_counter() - t0 < 0.01
