"""Data-layer tests: augmentation semantics vs numpy oracles, recipe
composition, two-crop independence, and the host pipeline."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from moco_tpu.data import (
    SyntheticDataset,
    TwoCropPipeline,
    V1_RECIPE,
    V2_RECIPE,
    apply_recipe,
    color_jitter,
    gaussian_blur,
    get_recipe,
    normalize,
    random_grayscale,
    random_horizontal_flip,
    random_resized_crop,
    two_crop_augment,
)
from moco_tpu.data.augment import (
    adjust_brightness,
    adjust_contrast,
    adjust_hue,
    adjust_saturation,
)
from moco_tpu.parallel import create_mesh
from moco_tpu.utils.config import DataConfig

RNG = jax.random.PRNGKey(0)


def rand_images(b=4, s=16):
    return jax.random.uniform(jax.random.PRNGKey(7), (b, s, s, 3))


class TestColorOps:
    def test_brightness_zero_is_black(self):
        img = rand_images()
        out = adjust_brightness(img, jnp.zeros((4, 1, 1, 1)))
        assert jnp.allclose(out, 0.0)

    def test_brightness_identity(self):
        img = rand_images()
        out = adjust_brightness(img, jnp.ones((4, 1, 1, 1)))
        np.testing.assert_allclose(out, img, atol=1e-6)

    def test_contrast_one_identity(self):
        img = rand_images()
        np.testing.assert_allclose(
            adjust_contrast(img, jnp.ones((4, 1, 1, 1))), img, atol=1e-6
        )

    def test_saturation_zero_is_gray(self):
        img = rand_images()
        out = adjust_saturation(img, jnp.zeros((4, 1, 1, 1)))
        assert jnp.allclose(out[..., 0], out[..., 1], atol=1e-6)
        assert jnp.allclose(out[..., 1], out[..., 2], atol=1e-6)

    def test_hue_zero_identity(self):
        img = rand_images()
        np.testing.assert_allclose(
            adjust_hue(img, jnp.zeros((4, 1, 1, 1))), img, atol=1e-5
        )

    def test_hue_full_turn_identity(self):
        img = rand_images()
        # delta=1.0 is a full rotation of the chroma plane
        np.testing.assert_allclose(
            adjust_hue(img, jnp.ones((4, 1, 1, 1))), img, atol=1e-4
        )

    def test_jitter_range(self):
        out = color_jitter(RNG, rand_images(), 0.4, 0.4, 0.4, 0.1)
        assert out.shape == (4, 16, 16, 3)
        assert float(out.min()) >= 0.0 and float(out.max()) <= 1.0

    def test_jitter_apply_prob_zero_identity(self):
        img = rand_images()
        out = color_jitter(RNG, img, 0.4, 0.4, 0.4, 0.1, apply_prob=0.0)
        np.testing.assert_allclose(out, img, atol=1e-6)


class TestGeometric:
    def test_flip_prob_one(self):
        img = rand_images()
        out = random_horizontal_flip(RNG, img, prob=1.0)
        np.testing.assert_allclose(out, img[:, :, ::-1, :])

    def test_flip_prob_zero(self):
        img = rand_images()
        np.testing.assert_allclose(random_horizontal_flip(RNG, img, prob=0.0), img)

    def test_crop_identity_when_full_scale(self):
        """scale=(1,1), ratio=(1,1) on square input = resize-only ≈ identity."""
        img = rand_images(2, 16)
        out = random_resized_crop(RNG, img, 16, scale=(1.0, 1.0), ratio=(1.0, 1.0))
        np.testing.assert_allclose(out, img, atol=1e-3)

    def test_crop_output_shape_and_range(self):
        img = rand_images(3, 32)
        out = random_resized_crop(RNG, img, 16)
        assert out.shape == (3, 16, 16, 3)
        assert bool(jnp.isfinite(out).all())
        assert float(out.min()) >= -1e-4 and float(out.max()) <= 1 + 1e-4

    def test_crops_differ_across_batch(self):
        img = jnp.broadcast_to(rand_images(1, 32), (4, 32, 32, 3))
        out = random_resized_crop(RNG, img, 16)
        assert not jnp.allclose(out[0], out[1])


class TestBlurGray:
    def test_grayscale_prob_one(self):
        out = random_grayscale(RNG, rand_images(), prob=1.0)
        assert jnp.allclose(out[..., 0], out[..., 2], atol=1e-6)

    def test_blur_matches_scipy_oracle(self):
        from scipy.ndimage import gaussian_filter

        img = np.asarray(rand_images(1, 16))
        sigma = 1.3
        out = gaussian_blur(
            RNG, jnp.asarray(img), sigma_range=(sigma, sigma), apply_prob=1.0, taps=13
        )
        want = np.stack(
            [gaussian_filter(img[0, ..., c], sigma, mode="nearest", truncate=6.0 / sigma)
             for c in range(3)],
            axis=-1,
        )
        np.testing.assert_allclose(np.asarray(out[0]), want, atol=5e-3)

    def test_blur_preserves_mean_roughly(self):
        img = rand_images(2, 16)
        out = gaussian_blur(RNG, img, apply_prob=1.0)
        np.testing.assert_allclose(jnp.mean(out), jnp.mean(img), atol=0.02)


class TestRecipes:
    def test_two_crops_differ_and_shapes(self):
        img = rand_images(4, 32)
        views = two_crop_augment(V2_RECIPE, RNG, img, 16)
        assert views["im_q"].shape == (4, 16, 16, 3)
        assert not jnp.allclose(views["im_q"], views["im_k"])

    def test_recipe_deterministic_in_rng(self):
        img = rand_images(2, 32)
        a = apply_recipe(V1_RECIPE, RNG, img, 16)
        b = apply_recipe(V1_RECIPE, RNG, img, 16)
        np.testing.assert_allclose(a, b)

    def test_normalize_stats(self):
        x = jnp.ones((1, 4, 4, 3)) * 0.5
        out = normalize(x, (0.5, 0.5, 0.5), (0.25, 0.25, 0.25))
        np.testing.assert_allclose(out, 0.0, atol=1e-6)

    def test_small_image_recipe_drops_blur(self):
        r = get_recipe(aug_plus=True, image_size=32)
        assert r.blur_prob == 0.0
        assert get_recipe(aug_plus=True, image_size=224).blur_prob == 0.5

    def test_recipes_jit_compile(self):
        img = rand_images(2, 32)
        fn = jax.jit(lambda r, x: apply_recipe(V2_RECIPE, r, x, 16))
        out = fn(RNG, img)
        assert bool(jnp.isfinite(out).all())


class TestPipeline:
    def test_two_crop_pipeline_epoch(self):
        mesh = create_mesh()
        cfg = DataConfig(dataset="synthetic", image_size=16, global_batch=16, num_workers=2)
        pipe = TwoCropPipeline(cfg, mesh, seed=0)
        batches = list(pipe.epoch(0))
        assert len(batches) == pipe.steps_per_epoch == 1024 // 16
        b = batches[0]
        assert b["im_q"].shape == (16, 16, 16, 3)
        assert not jnp.allclose(b["im_q"], b["im_k"])

    def test_epoch_shuffling_differs(self):
        mesh = create_mesh()
        cfg = DataConfig(dataset="synthetic", image_size=16, global_batch=16, num_workers=2)
        pipe = TwoCropPipeline(cfg, mesh, seed=0)
        b0 = next(iter(pipe.epoch(0)))
        b1 = next(iter(pipe.epoch(1)))
        assert not jnp.allclose(b0["im_q"], b1["im_q"])

    def test_batch_sharded_over_data_axis(self):
        mesh = create_mesh()
        cfg = DataConfig(dataset="synthetic", image_size=16, global_batch=16, num_workers=2)
        b = next(iter(TwoCropPipeline(cfg, mesh).epoch(0)))
        n = mesh.shape["data"]
        assert len(b["im_q"].addressable_shards) == jax.device_count()
        assert b["im_q"].addressable_shards[0].data.shape[0] == 16 // n

    def test_synthetic_dataset_deterministic(self):
        ds = SyntheticDataset(64, 16)
        a, la = ds.load(3)
        b, lb = ds.load(3)
        np.testing.assert_array_equal(a, b)
        assert la == lb


class TestHostCropPipeline:
    """Host-side RandomResizedCrop path (decode-once/crop-twice against
    original geometry) through both ImageFolder backends."""

    @pytest.fixture(scope="class")
    def folder(self, tmp_path_factory):
        from PIL import Image as PILImage

        root = tmp_path_factory.mktemp("hostcrop_imgs")
        rng = np.random.default_rng(0)
        for cls in ("a", "b"):
            (root / cls).mkdir()
            for i in range(20):
                h, w = rng.integers(40, 90, 2)
                arr = rng.integers(0, 256, (h, w, 3), dtype=np.uint8)
                PILImage.fromarray(arr).save(root / cls / f"i{i}.jpg", quality=92)
        return str(root)

    def test_two_crop_host_path(self, folder):
        mesh = create_mesh()
        cfg = DataConfig(
            dataset="imagefolder", data_dir=folder, image_size=16,
            global_batch=8, num_workers=2, host_rrc=True,
        )
        pipe = TwoCropPipeline(cfg, mesh, seed=0)
        assert pipe.host_crops  # both backends expose the protocol
        b = next(iter(pipe.epoch(0)))
        assert b["im_q"].shape == (8, 16, 16, 3)
        assert not jnp.allclose(b["im_q"], b["im_k"])  # independent crops
        assert bool(jnp.isfinite(b["im_q"]).all())

    def test_host_path_deterministic(self, folder):
        mesh = create_mesh()
        cfg = DataConfig(
            dataset="imagefolder", data_dir=folder, image_size=16,
            global_batch=8, num_workers=2, host_rrc=True,
        )
        a = next(iter(TwoCropPipeline(cfg, mesh, seed=3).epoch(0)))
        b = next(iter(TwoCropPipeline(cfg, mesh, seed=3).epoch(0)))
        np.testing.assert_allclose(np.asarray(a["im_q"]), np.asarray(b["im_q"]))

    def test_host_rrc_off_uses_canvas_path(self, folder):
        mesh = create_mesh()
        cfg = DataConfig(
            dataset="imagefolder", data_dir=folder, image_size=16,
            global_batch=8, num_workers=2, host_rrc=False,
        )
        pipe = TwoCropPipeline(cfg, mesh, seed=0)
        assert not pipe.host_crops
        b = next(iter(pipe.epoch(0)))
        assert b["im_q"].shape == (8, 16, 16, 3)

    def test_labeled_pipeline_host_path(self, folder):
        from moco_tpu.data.pipeline import LabeledPipeline

        mesh = create_mesh()
        cfg = DataConfig(
            dataset="imagefolder", data_dir=folder, image_size=16,
            global_batch=8, num_workers=2, host_rrc=True,
        )
        pipe = LabeledPipeline(cfg, mesh, seed=0)
        assert pipe.host_crops
        images, labels = next(iter(pipe.epoch(0)))
        assert images.shape == (8, 16, 16, 3)
        assert labels.shape == (8,)


class TestHardSyntheticDataset:
    """The harder learning-signal task (VERDICT r2 #7): class = power
    spectrum, instance = mask-filtered white noise. Validates the two
    design claims: raw pixels carry ~no class signal (kNN near the
    1/num_classes chance floor) while phase-invariant spectral features
    solve the task — i.e. the label IS the crop-invariant content."""

    @staticmethod
    def _feats(ds, mode):
        X = np.stack([ds.load(i)[0] for i in range(len(ds))]).astype(np.float32) / 255.0
        y = np.array([i % ds.num_classes for i in range(len(ds))])
        if mode == "pixel":
            F = X.reshape(len(ds), -1)
        else:  # FFT magnitude: phase-invariant spectral oracle
            F = np.abs(
                np.fft.rfft2(X - X.mean(axis=(1, 2), keepdims=True), axes=(1, 2))
            ).reshape(len(ds), -1)
        return F / (np.linalg.norm(F, axis=1, keepdims=True) + 1e-8), y

    @staticmethod
    def _knn(bx, by, tx, ty, num_classes, k=10):
        sims = tx @ bx.T
        idx = np.argpartition(-sims, k, axis=1)[:, :k]
        preds = [np.bincount(by[idx[r]], minlength=num_classes).argmax() for r in range(len(tx))]
        return 100.0 * np.mean(np.array(preds) == ty)

    def test_deterministic_and_disjoint_splits(self):
        from moco_tpu.data.datasets import HardSyntheticDataset

        a = HardSyntheticDataset(64, 32, 32, train=True)
        b = HardSyntheticDataset(64, 32, 32, train=True)
        np.testing.assert_array_equal(a.load(5)[0], b.load(5)[0])
        t = HardSyntheticDataset(64, 32, 32, train=False)
        assert not np.array_equal(a.load(5)[0], t.load(5)[0])
        assert a.load(5)[1] == t.load(5)[1] == 5 % 32

    def test_pixel_knn_at_chance_fft_oracle_high(self):
        from moco_tpu.data.datasets import HardSyntheticDataset

        bank = HardSyntheticDataset(1024, 32, 32, train=True)
        test = HardSyntheticDataset(256, 32, 32, train=False)
        chance = 100.0 / 32
        bx, by = self._feats(bank, "pixel")
        tx, ty = self._feats(test, "pixel")
        pixel = self._knn(bx, by, tx, ty, 32)
        bx, by = self._feats(bank, "fft")
        tx, ty = self._feats(test, "fft")
        fft = self._knn(bx, by, tx, ty, 32)
        # measured at these sizes: pixel ~6%, fft ~86%
        assert pixel < 4 * chance, f"pixel kNN {pixel:.1f}% leaks class signal"
        assert fft > 16 * chance, f"FFT oracle {fft:.1f}% — task not solvable from spectra"

    def test_build_dataset_hard(self):
        from moco_tpu.data.datasets import build_dataset

        ds = build_dataset("synthetic_hard", None, 32, train=False)
        assert ds.num_classes == 32 and len(ds) == 2048
        img, label = ds.load(0)
        assert img.shape == (32, 32, 3) and img.dtype == np.uint8


class TestHardTemplateDataset:
    """The rotation-template experiment (REPORT.md hard-signal section):
    statics hold (deterministic; pixel-kNN at chance via geometric
    decorrelation) even though the training gate failed — pinned so the
    recorded experiment stays reproducible."""

    def test_deterministic_and_pixel_knn_at_chance(self):
        from moco_tpu.data.datasets import HardTemplateDataset

        a = HardTemplateDataset(64, 32, 32, train=True)
        b = HardTemplateDataset(64, 32, 32, train=True)
        np.testing.assert_array_equal(a.load(7)[0], b.load(7)[0])

        bank = HardTemplateDataset(512, 32, 32, train=True)
        test = HardTemplateDataset(128, 32, 32, train=False)
        BX = np.stack([bank.load(i)[0] for i in range(512)]).astype(np.float32) / 255.0
        TX = np.stack([test.load(i)[0] for i in range(128)]).astype(np.float32) / 255.0
        by = np.array([i % 32 for i in range(512)])
        ty = np.array([i % 32 for i in range(128)])
        bx = BX.reshape(512, -1)
        tx = TX.reshape(128, -1)
        bx /= np.linalg.norm(bx, axis=1, keepdims=True) + 1e-8
        tx /= np.linalg.norm(tx, axis=1, keepdims=True) + 1e-8
        sims = tx @ bx.T
        idx = np.argpartition(-sims, 10, axis=1)[:, :10]
        preds = [np.bincount(by[idx[r]], minlength=32).argmax() for r in range(128)]
        acc = 100 * np.mean(np.array(preds) == ty)
        assert acc < 4 * (100.0 / 32), f"pixel kNN {acc:.1f}% leaks class signal"


class TestLeakControlDataset:
    """BN-cheat positive control (VERDICT r3 missing #3): the statics the
    adversarial design depends on — a weak crop-estimable tint as the
    ONLY content signal, and strong query/key co-batch fingerprint
    correlation at 2-row groups."""

    def test_deterministic_and_registered(self):
        from moco_tpu.data.datasets import (
            LeakControlSyntheticDataset,
            build_dataset,
        )

        a = LeakControlSyntheticDataset(64)
        b = LeakControlSyntheticDataset(64)
        img, label = a.load(11)
        np.testing.assert_array_equal(img, b.load(11)[0])
        assert img.shape == (32, 32, 3) and img.dtype == np.uint8
        assert label == 11 % 8
        ds = build_dataset("synthetic_leak_control", None, 32, train=True)
        assert isinstance(ds, LeakControlSyntheticDataset)
        # train/test draw disjoint instances
        t = build_dataset("synthetic_leak_control", None, 32, train=False)
        assert not np.array_equal(ds.load(0)[0], t.load(0)[0])

    def test_group_fingerprint_dominates_per_crop_signal(self):
        from moco_tpu.data.datasets import LeakControlSyntheticDataset

        ds = LeakControlSyntheticDataset(256)
        imgs = np.stack(
            [ds.load(i)[0].astype(np.float32) / 255.0 for i in range(256)]
        )
        # two disjoint 16x16 crops stand in for the two views
        q = imgs[:, :16, :16].mean(axis=(1, 2))
        k = imgs[:, 16:, 16:].mean(axis=(1, 2))
        # 2-row group means (the per-device BN stats at batch 16 over 8
        # devices): query-group vs key-group correlation must be strong —
        # this is the channel BN injects and Shuffle-BN severs
        gq = (q[0::2] + q[1::2]) / 2
        gk = (k[0::2] + k[1::2]) / 2
        corr = np.corrcoef(gq.ravel(), gk.ravel())[0, 1]
        assert corr > 0.5, f"co-batch fingerprint too weak: corr {corr:.2f}"

    def test_learnable32_registered_with_heavy_noise(self):
        from moco_tpu.data.datasets import (
            LearnableSyntheticDataset,
            build_dataset,
        )

        ds = build_dataset("synthetic_learnable32", None, 32, train=True)
        assert isinstance(ds, LearnableSyntheticDataset)
        assert ds.num_classes == 32 and ds.noise == 0.5


def test_crops_only_recipe_selection():
    from moco_tpu.data.augment import get_recipe

    r = get_recipe(True, 32, crops_only=True)
    assert r.jitter == (0.0, 0.0, 0.0, 0.0)
    assert r.grayscale_prob == 0.0 and r.blur_prob == 0.0
    assert r.crop and r.crop_scale == (0.2, 1.0)  # pretrain crop scale
    assert r.mean == (0.4914, 0.4822, 0.4465)  # cifar stats at 32px
    # default path unchanged
    assert get_recipe(True, 32).jitter[0] == 0.4


def test_imagefolder_counts_decode_failures(tmp_path):
    """Undecodable images zero-fill their crop slots, but COUNT — the
    pipeline surfaces the counter as the `decode_failures` metric so
    corrupt data is visible instead of silently training on black."""
    from PIL import Image

    from moco_tpu.data.datasets import ImageFolderDataset

    root = tmp_path / "imgs"
    (root / "a").mkdir(parents=True)
    rng = np.random.default_rng(0)
    good = rng.integers(0, 256, (40, 40, 3), dtype=np.uint8)
    Image.fromarray(good).save(root / "a" / "good.png")
    (root / "a" / "corrupt.jpg").write_bytes(b"\xff\xd8\xff not a real jpeg")

    ds = ImageFolderDataset(str(root), decode_size=16)
    assert ds.decode_failures == 0
    boxes = np.tile(np.array([[0, 0, 16, 16]], np.int64), (2, 1, 1))
    out, labels = ds.load_crop_batch(np.array([0, 1]), boxes, 8)
    # sorted listing: corrupt.jpg is index 0, good.png index 1
    assert ds.decode_failures == 1
    assert out[0].sum() == 0 and out[1].sum() > 0
    # the pipeline property reads straight through to the dataset
    from moco_tpu.utils.config import DataConfig

    mesh = create_mesh(num_data=1, num_model=1)
    pipe = TwoCropPipeline(
        DataConfig(dataset="synthetic", image_size=16, global_batch=1, num_workers=1),
        mesh,
        dataset=ds,
    )
    assert pipe.decode_failures == 1
