"""True multi-process integration test of the multi-host path.

Spawns TWO real OS processes that rendezvous through
`jax.distributed.initialize` (via `initialize_multihost`) on the CPU
backend — the same code path a multi-host TPU pod takes, minus the ICI.
This is the one test where process boundaries are real rather than
simulated with `addressable_devices` overrides (tests/test_dist.py):
collectives cross processes, each process can only address half the
mesh, and the input pipeline must decode only its own global-batch rows.

Reference equivalents: `dist.init_process_group` (`main_moco.py:~L150`)
and `DistributedSampler` (`~L258`).
"""

import json
import os
import socket
import subprocess
import sys
import threading

import jax
import pytest

# Cross-process collectives over the CPU backend need jaxlib's
# multi-process CPU support (jax >= 0.5): older jaxlibs fail with
# "Multiprocess computations aren't implemented on the CPU backend".
# Gate on the capability rather than fail — the single-process mesh
# tests (test_dist.py, test_train_step.py) still cover the collective
# semantics on such environments.
pytestmark = pytest.mark.skipif(
    jax.__version_info__ < (0, 5),
    reason="this jaxlib lacks multi-process CPU collectives",
)

WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)), "_multihost_worker.py")
NPROC = 2
DEVICES_PER_PROC = 2


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _worker_env() -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = [
        f
        for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    ]
    flags.append(f"--xla_force_host_platform_device_count={DEVICES_PER_PROC}")
    env["XLA_FLAGS"] = " ".join(flags)
    # a worker must not inherit a half-configured distributed env
    for k in ("JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES", "JAX_PROCESS_ID"):
        env.pop(k, None)
    return env


def _run_pair(extra_args: list[str] | None = None) -> list[dict]:
    """Spawn a 2-process world, drain both workers concurrently, return
    their JSON evidence lines. Concurrent drain matters: a full stderr
    pipe on one worker mid-collective would block its peer too, and a
    sequential communicate() would read that as a spurious timeout."""
    addr = f"127.0.0.1:{_free_port()}"
    env = _worker_env()
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, addr, str(pid), str(NPROC), *(extra_args or [])],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        for pid in range(NPROC)
    ]
    results: dict[int, tuple] = {}

    def drain(i, p):
        results[i] = p.communicate(timeout=560)

    outs = []
    try:
        threads = [
            threading.Thread(target=drain, args=(i, p), daemon=True)
            for i, p in enumerate(procs)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=580)
        for i, p in enumerate(procs):
            assert i in results, f"worker {i} did not complete in time"
            out, err = results[i]
            assert p.returncode == 0, f"worker failed rc={p.returncode}\n{err[-4000:]}"
            outs.append(json.loads(out.strip().splitlines()[-1]))
    finally:
        # a hung rendezvous must not leak workers (and the coordinator
        # port) past the test
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    return outs


def test_two_process_world_trains_in_lockstep():
    outs = _run_pair()
    by_pid = {o["process"]: o for o in outs}
    assert set(by_pid) == {0, 1}
    for o in outs:
        assert o["process_count"] == NPROC
        assert o["world_devices"] == NPROC * DEVICES_PER_PROC
        assert o["local_devices"] == DEVICES_PER_PROC
        # DistributedSampler equivalent: each process decoded exactly its
        # half of the global batch
        assert o["local_rows"] == o["global_batch"] // NPROC
        assert o["final_step"] == 2
        assert all(l == l and abs(l) < 1e6 for l in o["losses"])  # finite

    # the two halves tile the global batch exactly
    rows0 = set(by_pid[0]["local_positions"])
    rows1 = set(by_pid[1]["local_positions"])
    assert rows0.isdisjoint(rows1)
    assert rows0 | rows1 == set(range(outs[0]["global_batch"]))

    # replicated lockstep: the SPMD program is identical on both
    # processes, so the replicated loss must match bit-for-bit
    assert by_pid[0]["losses"] == by_pid[1]["losses"]


def test_checkpoint_restore_continuity_across_restart(tmp_path):
    """The reference's recovery story is manual `--resume` from the last
    checkpoint (`main_moco.py:~L195-215`). The multi-host equivalent:
    a 2-process world saves mid-run via Orbax, BOTH processes restart
    (a fresh rendezvous), restore, and continue — and the continuation
    must be bit-identical to the run that never stopped (params, opt
    state, queue+ptr, EMA encoder, and the step counter that seeds the
    per-step shuffle RNG all round-tripped exactly), on both processes.
    """
    workdir = str(tmp_path / "ckpt")
    saved = _run_pair(["save", workdir])
    by_pid = {o["process"]: o for o in saved}
    assert by_pid[0]["post_losses"] == by_pid[1]["post_losses"]
    oracle = by_pid[0]["post_losses"]  # uninterrupted continuation
    assert by_pid[0]["final_step"] == 4

    restored = _run_pair(["restore", workdir])
    r_by_pid = {o["process"]: o for o in restored}
    for o in restored:
        assert o["restored_step"] == 2
        assert o["restored_epoch"] == 0
        assert o["final_step"] == 4
    # lockstep across the restarted processes...
    assert r_by_pid[0]["post_losses"] == r_by_pid[1]["post_losses"]
    # ...and bit-identical to the run that never restarted
    assert r_by_pid[0]["post_losses"] == oracle
