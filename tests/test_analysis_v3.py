"""mocolint v3: the thread-escape + lock-set model (analysis/threads.py),
the concurrency rules JX012/JX013 and the AOT freeze rule JX014, the
`--changed` fast pre-pass, and the runtime lock-order sanitizer
(analysis/tsan.py) with its `deadlock@site` chaos hook."""

import json
import os
import queue
import subprocess
import threading

import pytest

from moco_tpu.analysis import analyze_source, tsan
from moco_tpu.analysis.__main__ import main as mocolint_main
from moco_tpu.analysis.engine import Finding, parse_module
from moco_tpu.analysis.threads import component_models
from moco_tpu.utils import faults

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _model(src: str, cls: str = None):
    ctx = parse_module(src, "m.py")
    assert not isinstance(ctx, Finding)
    models = component_models(ctx)
    if cls is None:
        assert len(models) == 1
        return models[0]
    return next(m for m in models if m.name == cls)


# ---------------------------------------------------------------------------
# thread-escape model


def test_thread_target_and_public_roots():
    m = _model(
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._t = threading.Thread(target=self._run)\n"
        "    def _run(self):\n"
        "        self.x = 1\n"
        "    def poke(self):\n"
        "        self.x = 2\n"
    )
    assert m.roots["_run"] == {"thread:_run"}
    assert "main" in m.roots["poke"]


def test_http_handler_methods_are_many_threaded_roots():
    m = _model(
        "import http.server\n"
        "class S:\n"
        "    def __init__(self):\n"
        "        server = self\n"
        "        class Handler(http.server.BaseHTTPRequestHandler):\n"
        "            def do_GET(self):\n"
        "                server.hits += 1\n"
    )
    assert m.roots["Handler.do_GET"] == {"http:do_GET"}
    assert m.thread_weight("http:do_GET") == 2  # one thread per request
    shared = list(m.shared_attr_accesses())
    assert [attr for attr, _, _ in shared] == ["hits"]


def test_callback_escape_is_a_root_but_property_is_not():
    m = _model(
        "class C:\n"
        "    def __init__(self, batcher, fmt):\n"
        "        batcher(self._on_done)\n"
        "        fmt(self.avg)\n"
        "    def _on_done(self):\n"
        "        self.n += 1\n"
        "    @property\n"
        "    def avg(self):\n"
        "        self.n += 1\n"
        "        return self.n\n"
    )
    assert m.roots["_on_done"] == {"callback:_on_done"}
    # the property is a public READ (main root) but NOT a callback escape
    assert "callback:avg" not in m.roots["avg"]


def test_alias_resolves_to_component_and_nested_self_calls():
    m = _model(
        "import threading\n"
        "class S:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        server = self\n"
        "        class Handler:\n"
        "            def do_POST(self):\n"
        "                self._helper()\n"
        "            def _helper(self):\n"
        "                with server._lock:\n"
        "                    server.rows += 1\n"
    )
    # do_POST -> Handler._helper resolved; the helper's write is rooted
    # and carries the alias-resolved lock
    writes = [a for a in m.accesses if a.attr == "rows" and a.is_write]
    assert writes and writes[0].locks == frozenset({"self._lock"})
    assert m.roots["Handler._helper"] == {"http:do_POST"}


def test_inherited_lock_through_private_helper():
    m = _model(
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "    def flush(self):\n"
        "        with self._lock:\n"
        "            self._write()\n"
        "    def _write(self):\n"
        "        self.n += 1\n"
    )
    writes = [a for a in m.accesses if a.attr == "n" and a.is_write]
    assert writes[0].locks == frozenset({"self._lock"})


def test_safe_typed_attrs_are_exempt():
    m = _model(
        "import queue, threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._q = queue.Queue()\n"
        "        self._stop = threading.Event()\n"
        "        self._t = threading.Thread(target=self._run)\n"
        "    def _run(self):\n"
        "        self._q.put(1)\n"
        "    def close(self):\n"
        "        self._q.put(None)\n"
        "        self._stop.set()\n"
    )
    assert list(m.shared_attr_accesses()) == []


# ---------------------------------------------------------------------------
# JX012 semantics on inline snippets


def test_jx012_common_lock_is_clean():
    src = (
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._t = threading.Thread(target=self._run)\n"
        "        self._t.start()\n"
        "    def _run(self):\n"
        "        with self._lock:\n"
        "            self.n = 1\n"
        "    def read(self):\n"
        "        with self._lock:\n"
        "            return self.n\n"
        "    def close(self):\n"
        "        self._t.join()\n"
    )
    assert analyze_source(src, "m.py", rules=["JX012"]) == []


def test_jx012_flags_unlocked_read_of_guarded_attr():
    src = (
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._t = threading.Thread(target=self._run)\n"
        "        self._t.start()\n"
        "    def _run(self):\n"
        "        with self._lock:\n"
        "            self.n = 1\n"
        "    def read(self):\n"
        "        return self.n\n"
        "    def close(self):\n"
        "        self._t.join()\n"
    )
    findings = analyze_source(src, "m.py", rules=["JX012"])
    assert len(findings) == 1 and "without lock 'self._lock'" in findings[0].message


# ---------------------------------------------------------------------------
# JX013 semantics


def test_jx013_consistent_order_is_clean():
    src = (
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._a_lock = threading.Lock()\n"
        "        self._b_lock = threading.Lock()\n"
        "    def one(self):\n"
        "        with self._a_lock:\n"
        "            with self._b_lock:\n"
        "                pass\n"
        "    def two(self):\n"
        "        with self._a_lock:\n"
        "            with self._b_lock:\n"
        "                pass\n"
    )
    assert analyze_source(src, "m.py", rules=["JX013"]) == []


def test_jx013_cycle_through_inherited_lock():
    src = (
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._a_lock = threading.Lock()\n"
        "        self._b_lock = threading.Lock()\n"
        "    def one(self):\n"
        "        with self._a_lock:\n"
        "            self._inner()\n"
        "    def _inner(self):\n"
        "        with self._b_lock:\n"
        "            pass\n"
        "    def two(self):\n"
        "        with self._b_lock:\n"
        "            with self._a_lock:\n"
        "                pass\n"
    )
    findings = analyze_source(src, "m.py", rules=["JX013"])
    assert len(findings) == 1 and "lock-order cycle" in findings[0].message


def test_jx013_blocking_sleep_under_lock():
    src = (
        "import threading, time\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "    def slow(self):\n"
        "        with self._lock:\n"
        "            time.sleep(5)\n"
    )
    findings = analyze_source(src, "m.py", rules=["JX013"])
    assert len(findings) == 1 and "time.sleep" in findings[0].message


# ---------------------------------------------------------------------------
# JX014 semantics


def test_jx014_guarded_seam_is_clean():
    src = (
        "import jax\n"
        "class E:\n"
        "    def freeze(self):\n"
        "        self._frozen = True\n"
        "    def _compile(self, bucket):\n"
        "        if self._frozen:\n"
        "            raise RuntimeError(bucket)\n"
        "        return jax.jit(self._f).lower(bucket).compile()\n"
        "    def run(self, images):\n"
        "        return self._compile(images.shape[0])\n"
    )
    assert analyze_source(src, "m.py", rules=["JX014"]) == []


def test_jx014_raw_shape_to_unguarded_seam():
    src = (
        "import jax\n"
        "class E:\n"
        "    def freeze(self):\n"
        "        self._frozen = True\n"
        "    def _compile(self, bucket):\n"
        "        return jax.jit(self._f).lower(bucket).compile()\n"
        "    def run(self, images):\n"
        "        return self._compile(images.shape[0])\n"
    )
    findings = analyze_source(src, "m.py", rules=["JX014"])
    assert len(findings) == 1 and "compile seam" in findings[0].message


def test_jx014_bucket_for_sanitizes():
    src = (
        "import jax\n"
        "class E:\n"
        "    def freeze(self):\n"
        "        self._frozen = True\n"
        "    def bucket_for(self, n):\n"
        "        return min(b for b in self.buckets if n <= b)\n"
        "    def _compile(self, bucket):\n"
        "        return jax.jit(self._f).lower(bucket).compile()\n"
        "    def run(self, images):\n"
        "        return self._compile(self.bucket_for(images.shape[0]))\n"
    )
    assert analyze_source(src, "m.py", rules=["JX014"]) == []


# ---------------------------------------------------------------------------
# --changed mode


def test_changed_mode_lints_only_the_diff(tmp_path, capsys):
    repo = tmp_path / "r"
    repo.mkdir()

    def git(*args):
        subprocess.run(
            ["git", "-c", "user.email=t@t", "-c", "user.name=t", *args],
            cwd=repo, check=True, capture_output=True,
        )

    git("init", "-q")
    clean = repo / "clean.py"
    clean.write_text("import time\n\n\ndef ok():\n    return time.time()\n")
    git("add", "-A")
    git("commit", "-qm", "base")
    bad = repo / "bad.py"
    bad.write_text(
        "import time\nimport jax\n\n\n@jax.jit\ndef f(x):\n    return x + time.time()\n"
    )
    git("add", "-A")
    git("commit", "-qm", "bad")
    cwd = os.getcwd()
    os.chdir(repo)
    try:
        # vs HEAD~1 only bad.py is linted -> findings -> exit 1
        assert mocolint_main([".", "--no-baseline", "--changed", "HEAD~1"]) == 1
        out = capsys.readouterr().out
        assert "linting 1 file(s)" in out and "bad.py" in out
        # vs HEAD nothing changed -> exit 0 without analyzing
        assert mocolint_main([".", "--no-baseline", "--changed", "HEAD"]) == 0
        assert "no python files changed" in capsys.readouterr().out
    finally:
        os.chdir(cwd)


# ---------------------------------------------------------------------------
# runtime arm: tsan


@pytest.fixture
def clean_tsan():
    prev = tsan.install_recorder(None)
    yield
    tsan.install_recorder(prev)
    faults.clear()


def test_traced_lock_is_plain_without_recorder(clean_tsan):
    lk = tsan.make_lock("x")
    with lk:
        assert lk.locked()
    assert not lk.locked()


def test_ab_ba_cycle_raises_with_artifact(tmp_path, clean_tsan):
    san = tsan.ThreadSanitizer(workdir=str(tmp_path), strict=True, profile=False)
    try:
        a, b = tsan.make_lock("a"), tsan.make_lock("b")

        def ab():
            with a:
                with b:
                    pass

        t = threading.Thread(target=ab)
        t.start()
        t.join()
        with pytest.raises(tsan.LockOrderError):
            with b:
                with a:  # the inverted order: caught BEFORE blocking
                    pass
    finally:
        rep = san.close()
    assert rep["cycles"], rep
    diff = json.loads((tmp_path / "lock_order_diff.json").read_text())
    assert diff["cycle"][0] == diff["cycle"][-1]
    # both directions present, each with a recorded stack
    dirs = {(e["held"], e["acquired"]) for e in diff["edges"]}
    assert dirs == {("a", "b"), ("b", "a")}
    assert all(e["stack"] for e in diff["edges"])


def test_deadlock_fault_forces_inverted_edge(tmp_path, clean_tsan):
    faults.install("deadlock@site=inner")
    san = tsan.ThreadSanitizer(workdir=str(tmp_path), strict=False, profile=False)
    try:
        outer, inner = tsan.make_lock("outer"), tsan.make_lock("inner")
        with outer:
            with inner:  # the ONLY nesting — the fault synthesizes BA
                pass
    finally:
        rep = san.close()
    assert len(rep["cycles"]) == 1
    injected = [e for e in rep["edges"] if e["injected"]]
    assert injected == [{"held": "inner", "acquired": "outer", "injected": True}]
    assert (tmp_path / "lock_order_diff.json").exists()


def test_sanitizer_check_raises_on_recorded_cycle(tmp_path, clean_tsan):
    faults.install("deadlock@site=i2")
    san = tsan.ThreadSanitizer(workdir=str(tmp_path), strict=False, profile=False)
    o, i = tsan.make_lock("o2"), tsan.make_lock("i2")
    with o:
        with i:
            pass
    with pytest.raises(tsan.LockOrderError):
        san.check()
    san.close()


def test_profile_hook_records_blocking_ops_under_lock(clean_tsan):
    san = tsan.ThreadSanitizer(workdir=None, strict=True, profile=True)
    try:
        lk = tsan.make_lock("held")
        q = queue.Queue()
        q.put("primed")
        with lk:
            q.put(1)          # unbounded put: recorded
            q.get()           # blocking get: recorded
        q.get(timeout=1.0)    # bounded AND no lock held: not recorded
    finally:
        rep = san.close()
    ops = [b["op"] for b in rep["blocking_ops_under_lock"]]
    assert any("put" in o for o in ops) and any("get" in o for o in ops)
    assert all(b["held"] == ["held"] for b in rep["blocking_ops_under_lock"])


def test_rlock_reentry_does_not_self_edge(clean_tsan):
    san = tsan.ThreadSanitizer(workdir=None, strict=True, profile=False)
    try:
        r = tsan.make_rlock("r")
        with r:
            with r:  # re-entry: no r->r edge, no cycle
                pass
    finally:
        rep = san.close()
    assert rep["edges"] == [] and rep["cycles"] == []


# ---------------------------------------------------------------------------
# the serve-shaped smoke leg (slow): real batcher + metrics under the
# sanitizer — a clean pass with genuine lock traffic


@pytest.mark.slow
def test_batcher_clean_under_sanitize_threads(clean_tsan):
    import numpy as np

    from moco_tpu.serve.batcher import ContinuousBatcher, ServeMetrics

    san = tsan.ThreadSanitizer(workdir=None, strict=True, profile=True)
    try:
        metrics = ServeMetrics(slo_ms=1000.0)
        index_lock = tsan.make_lock("serve.index")

        def run_batch(images, want_neighbors):
            with index_lock:  # the server's sanctioned nesting shape
                payload = metrics.payload()
            assert payload["serve/slo_ms"] == 1000.0
            return {"embedding": np.zeros((images.shape[0], 4), np.float32)}, [
                (images.shape[0], images.shape[0])
            ]

        batcher = ContinuousBatcher(run_batch, max_batch=8, slo_ms=50.0, metrics=metrics)
        futs = [batcher.submit(np.zeros((2, 4, 4, 3), np.uint8)) for _ in range(6)]
        for f in futs:
            f.result(timeout=10.0)
        batcher.close()
    finally:
        rep = san.close()
    assert rep["cycles"] == []
    assert rep["acquisitions"] > 0
    edges = {(e["held"], e["acquired"]) for e in rep["edges"]}
    assert ("serve.index", "serve.metrics") in edges
