"""End-to-end SPMD train-step tests on the 8-virtual-device CPU mesh.

Covers the reference's hot path (SURVEY.md §3.1): EMA ordering, queue
FIFO lockstep, shuffle-BN, gradient reduction — plus the TPU-only
extras (syncbn equivalence, model-sharded queue)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from moco_tpu.core import MoCoEncoder, create_state, make_train_step
from moco_tpu.models import ProjectionHead, ResNet, BasicBlock
from moco_tpu.ops import l2_normalize
from moco_tpu.parallel import create_mesh
from moco_tpu.utils.config import DataConfig, MocoConfig, OptimConfig, TrainConfig
from moco_tpu.utils.schedules import build_optimizer

DIM = 16
BATCH = 16
IMG = 8
K = 128


def tiny_config(**moco_kw):
    moco = MocoConfig(
        arch="tiny", dim=DIM, num_negatives=K, temperature=0.1, compute_dtype="float32", **moco_kw
    )
    return TrainConfig(
        moco=moco,
        optim=OptimConfig(lr=0.1, epochs=4, cos=True),
        data=DataConfig(dataset="synthetic", image_size=IMG, global_batch=BATCH),
    )


def tiny_encoder(mlp=False, syncbn=False):
    backbone = ResNet(
        stage_sizes=[1, 1],
        block=BasicBlock,
        num_filters=8,
        cifar_stem=True,
        bn_cross_replica_axis="data" if syncbn else None,
    )
    return MoCoEncoder(backbone=backbone, head=ProjectionHead(dim=DIM, mlp=mlp))


def make_batch(seed=0):
    r1, r2 = jax.random.split(jax.random.key(seed))
    return {
        "im_q": jax.random.normal(r1, (BATCH, IMG, IMG, 3)),
        "im_k": jax.random.normal(r2, (BATCH, IMG, IMG, 3)),
    }


def setup(config, num_data=8, num_model=1, mlp=False):
    mesh = create_mesh(num_data=num_data, num_model=num_model)
    enc = tiny_encoder(mlp, syncbn=config.moco.shuffle == "syncbn")
    tx = build_optimizer(config.optim, steps_per_epoch=10)
    state = create_state(jax.random.key(0), config, enc, tx, jnp.zeros((1, IMG, IMG, 3)))
    step = make_train_step(config, enc, tx, mesh)
    return mesh, enc, tx, state, step


@pytest.mark.parametrize("shuffle", ["gather_perm", "a2a", "syncbn", "none"])
def test_step_runs_and_updates(shuffle):
    config = tiny_config(shuffle=shuffle)
    # a2a needs local batch divisible by the axis size: 16/4=4 per device
    _, _, _, state, step = setup(config, num_data=4 if shuffle == "a2a" else 8)
    p0 = jax.tree.map(np.array, state.params_q)
    k0 = jax.tree.map(np.array, state.params_k)
    state, metrics = step(state, make_batch(), jax.random.key(1))
    assert np.isfinite(float(metrics["loss"]))
    assert 0.0 <= float(metrics["acc1"]) <= 100.0
    assert int(state.queue_ptr) == BATCH
    assert int(state.step) == 1
    # params moved, EMA moved toward (old) q
    moved = jax.tree.map(lambda a, b: not np.allclose(a, b), p0, state.params_q)
    assert any(jax.tree.leaves(moved))
    m = config.moco.momentum
    want_k = jax.tree.map(lambda kk, qq: kk * m + qq * (1 - m), k0, p0)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), b, rtol=1e-4, atol=1e-5),
        state.params_k,
        want_k,
    )


def test_queue_contents_oracle_single_device():
    """1-device mesh, no shuffle: recompute the key path externally and
    check the FIFO block matches (moco/builder.py:~L62-77 semantics)."""
    config = tiny_config(shuffle="none")
    mesh, enc, tx, state, step = setup(config, num_data=1)
    batch = make_batch()
    k0 = jax.tree.map(np.array, state.params_k)
    q0 = jax.tree.map(np.array, state.params_q)
    stats_k0 = jax.tree.map(np.array, state.batch_stats_k)
    queue0 = np.array(state.queue)
    state, _ = step(state, batch, jax.random.key(1))
    # external recompute: EMA first, then key forward in train mode
    m = config.moco.momentum
    params_k = jax.tree.map(lambda kk, qq: kk * m + qq * (1 - m), k0, q0)
    want_k, _ = enc.apply(
        {"params": params_k, "batch_stats": stats_k0},
        batch["im_k"],
        train=True,
        mutable=["batch_stats"],
    )
    want_k = np.asarray(l2_normalize(want_k))
    got = np.array(state.queue)
    np.testing.assert_allclose(got[:BATCH], want_k, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(got[BATCH:], queue0[BATCH:], rtol=1e-6)


def test_syncbn_8dev_matches_single_device_globalbn():
    """SyncBN over the whole data axis must reproduce single-device BN
    exactly: same loss, same updated params."""
    batch = make_batch(5)
    cfg_multi = tiny_config(shuffle="syncbn")
    _, _, _, s8, step8 = setup(cfg_multi, num_data=8)
    s8, m8 = step8(s8, batch, jax.random.key(2))

    cfg_one = tiny_config(shuffle="none")
    _, _, _, s1, step1 = setup(cfg_one, num_data=1)
    s1, m1 = step1(s1, batch, jax.random.key(2))

    np.testing.assert_allclose(float(m8["loss"]), float(m1["loss"]), rtol=1e-4)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-3, atol=1e-5),
        s8.params_q,
        s1.params_q,
    )


def test_model_sharded_queue_matches_replicated():
    """(data=4, model=2) with the queue sharded over `model` must produce
    the same queue and loss as the replicated-queue run."""
    batch = make_batch(7)
    cfg = tiny_config(shuffle="gather_perm")
    _, _, _, s_rep, step_rep = setup(cfg, num_data=4, num_model=1)
    _, _, _, s_sh, step_sh = setup(cfg, num_data=4, num_model=2)
    for seed in range(3):
        b = make_batch(10 + seed)
        s_rep, m_rep = step_rep(s_rep, b, jax.random.key(3))
        s_sh, m_sh = step_sh(s_sh, b, jax.random.key(3))
        # must match to float noise at EVERY step: grads are pmean'd over
        # (data, model) so the replicated-params invariant holds exactly
        np.testing.assert_allclose(float(m_rep["loss"]), float(m_sh["loss"]), rtol=2e-4)
    np.testing.assert_allclose(
        np.array(s_rep.queue), np.array(s_sh.queue), rtol=1e-3, atol=1e-5
    )
    assert int(s_sh.queue_ptr) == 3 * BATCH


def test_local_bn_differs_from_syncbn():
    """With shuffle='none' on 8 devices BN stats are per-device — the
    statistics the leak rides on. Sanity-check they differ from syncbn
    (i.e. our BN modes are actually different programs)."""
    batch = make_batch(9)
    _, _, _, sl, stepl = setup(tiny_config(shuffle="none"), num_data=8)
    _, _, _, ss, steps_ = setup(tiny_config(shuffle="syncbn"), num_data=8)
    sl, ml = stepl(sl, batch, jax.random.key(4))
    ss, ms = steps_(ss, batch, jax.random.key(4))
    assert not np.allclose(float(ml["loss"]), float(ms["loss"]), rtol=1e-6)


def test_determinism():
    config = tiny_config(shuffle="gather_perm")
    batch = make_batch(11)
    _, _, _, s1, step1 = setup(config)
    _, _, _, s2, step2 = setup(config)
    s1, m1 = step1(s1, batch, jax.random.key(0))
    s2, m2 = step2(s2, batch, jax.random.key(0))
    assert float(m1["loss"]) == float(m2["loss"])
    np.testing.assert_array_equal(np.array(s1.queue), np.array(s2.queue))


def test_a2a_shuffle_changes_bn_program_vs_none():
    """Regression for the removed `ring` mode, which was bit-identical to
    shuffle='none': a real shuffle changes per-device BN batches, so the
    loss must differ from the unshuffled program."""
    batch = make_batch(13)
    _, _, _, sa, stepa = setup(tiny_config(shuffle="a2a"), num_data=4)
    _, _, _, sn, stepn = setup(tiny_config(shuffle="none"), num_data=4)
    sa, ma = stepa(sa, batch, jax.random.key(6))
    sn, mn = stepn(sn, batch, jax.random.key(6))
    assert float(ma["loss"]) != float(mn["loss"])
    # ...but the k_global fed to the queue is the same *set* of examples
    # in original order, so queues agree up to BN-statistics effects only.
    assert int(sa.queue_ptr) == int(sn.queue_ptr) == BATCH


def test_queue_wraps_over_epochs():
    config = tiny_config(shuffle="gather_perm")
    _, _, _, state, step = setup(config)
    for i in range(K // BATCH + 1):
        state, _ = step(state, make_batch(i), jax.random.key(1))
    assert int(state.queue_ptr) == BATCH  # wrapped past K


class TestKeyBnRunningStats:
    """EMAN-style key forward (MocoConfig.key_bn_running_stats): the key
    encoder runs eval-mode BN, its running statistics EMA-track the
    query's, and the incompatible-config gates fail loudly."""

    @pytest.mark.parametrize("warmup", [True, False])
    def test_step_runs_and_stats_track_query(self, warmup):
        config = tiny_config(
            shuffle="none",
            key_bn_running_stats=True,
            key_bn_stats_warmup=warmup,
            momentum=0.9,
        )
        _, _, _, state, step = setup(config)
        k_stats0 = jax.tree.map(np.array, state.batch_stats_k)
        state, metrics = step(state, make_batch(), jax.random.key(1))
        assert np.isfinite(float(metrics["loss"]))
        # batch_stats_k must be EXACTLY the EMA of its old value toward
        # the new (pmean'd) query statistics — the lockstep invariant.
        # With the warmup schedule, step 0's momentum fast-tracks to
        # min(0.9, (1+0)/(10+0)) = 0.1 (the num_updates schedule).
        m = min(0.9, 0.1) if warmup else 0.9
        expected = jax.tree.map(
            lambda old, q: m * old + (1 - m) * np.asarray(q),
            k_stats0,
            jax.tree.map(np.array, state.batch_stats_q),
        )
        chex = jax.tree.map(
            lambda a, b: np.allclose(a, b, rtol=1e-5, atol=1e-6),
            expected,
            jax.tree.map(np.array, state.batch_stats_k),
        )
        assert all(jax.tree.leaves(chex))

    def test_syncbn_composes(self):
        """shuffle='syncbn' is the allowed multi-device companion: the
        query side keeps cross-replica statistics while the key side
        stays on running stats."""
        config = tiny_config(shuffle="syncbn", key_bn_running_stats=True)
        _, _, _, state, step = setup(config)
        _, metrics = step(state, make_batch(), jax.random.key(1))
        assert np.isfinite(float(metrics["loss"]))

    def test_rejected_with_shuffle_or_v3(self):
        for bad in ("gather_perm", "a2a"):
            config = tiny_config(shuffle=bad, key_bn_running_stats=True)
            with pytest.raises(ValueError, match="key_bn_running_stats"):
                setup(config)
        config = tiny_config(shuffle="none", key_bn_running_stats=True)
        config = dataclasses.replace(
            config,
            moco=dataclasses.replace(config.moco, v3=True, num_negatives=0),
        )
        with pytest.raises(ValueError, match="key_bn_running_stats"):
            setup(config)
