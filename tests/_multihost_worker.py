"""Two-process multi-host worker (spawned by tests/test_multihost.py).

Each worker is a REAL separate process that joins a 2-process
`jax.distributed` world over the CPU backend (the same rendezvous path a
TPU pod host takes — `initialize_multihost` wraps
`jax.distributed.initialize`, the NCCL `init_process_group` equivalent,
`main_moco.py:~L150`). With a 2-virtual-device CPU platform per process
the world is a 4-device mesh spanning both processes; the worker then
runs the full MoCo pretrain step — cross-process shuffle-BN gather-perm,
queue enqueue, gradient psum — while its input pipeline decodes ONLY the
global-batch rows its own devices own (DistributedSampler equivalent,
`main_moco.py:~L258`).

Prints one JSON line of per-process evidence for the parent to compare:
losses must match bit-for-bit across processes (lockstep replicated
state) and each process must have decoded exactly half the global batch.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np


def main() -> None:
    addr, pid, nproc = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
    # checkpoint-continuity phases (VERDICT r2 #6): 'save' runs two
    # steps, checkpoints the 2-process world via Orbax, then keeps going
    # (its later losses are the uninterrupted-run oracle); 'restore' is a
    # FRESH process pair that restores that checkpoint and continues —
    # the parent asserts its losses equal the oracle bit-for-bit.
    phase = sys.argv[4] if len(sys.argv) > 4 else "plain"
    workdir = sys.argv[5] if len(sys.argv) > 5 else None

    from moco_tpu.parallel import initialize_multihost

    initialize_multihost(coordinator_address=addr, num_processes=nproc, process_id=pid)
    assert jax.process_count() == nproc, jax.process_count()

    from moco_tpu.core import build_encoder, create_state, make_train_step, place_state
    from moco_tpu.data.pipeline import TwoCropPipeline
    from moco_tpu.parallel import create_mesh
    from moco_tpu.utils.config import (
        DataConfig,
        MocoConfig,
        OptimConfig,
        TrainConfig,
    )
    from moco_tpu.utils.schedules import build_optimizer

    world = jax.devices()
    num_data = len(world)
    mesh = create_mesh(num_data=num_data, num_model=1)
    batch = 2 * num_data
    img = 32
    config = TrainConfig(
        moco=MocoConfig(
            arch="resnet18",
            dim=32,
            num_negatives=batch * 4,
            temperature=0.2,
            mlp=True,
            shuffle="gather_perm",  # cross-PROCESS permutation collective
            cifar_stem=True,
            compute_dtype="float32",
        ),
        optim=OptimConfig(lr=0.03, epochs=1, cos=True),
        data=DataConfig(
            dataset="synthetic", image_size=img, global_batch=batch, num_workers=2
        ),
    )

    pipe = TwoCropPipeline(config.data, mesh, seed=0)
    part = pipe._partition
    assert not part.is_trivial, "partition must be non-trivial across 2 processes"

    encoder = build_encoder(config.moco, num_data=num_data)
    tx = build_optimizer(config.optim, steps_per_epoch=pipe.steps_per_epoch)
    state = create_state(
        jax.random.PRNGKey(0), config, encoder, tx, jnp.zeros((1, img, img, 3))
    )
    state = place_state(state, mesh)
    step_fn = make_train_step(config, encoder, tx, mesh)
    root_rng = jax.device_put(
        jax.random.PRNGKey(2),
        jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec()),
    )

    def run_steps(state, epoch: int, n: int):
        losses = []
        for _step, batch_dict in zip(range(n), pipe.epoch(epoch)):
            state, metrics = step_fn(state, batch_dict, root_rng)
            # loss is fully replicated -> addressable from every process
            losses.append(float(jax.device_get(metrics["loss"])))
        return state, losses

    evidence = {}
    if phase == "restore":
        # fresh process pair: restore the 'save' phase's checkpoint into
        # the freshly-initialized template, then continue epoch 1 exactly
        # as the uninterrupted run did
        from moco_tpu.utils.checkpoint import CheckpointManager

        mgr = CheckpointManager(workdir)
        state, extra = mgr.restore(state)
        mgr.close()
        state = place_state(state, mesh)
        assert int(state.step) == 2, int(state.step)
        evidence["restored_step"] = int(state.step)
        evidence["restored_epoch"] = int(extra.get("epoch", -1))
        state, losses = run_steps(state, epoch=1, n=2)
        evidence["post_losses"] = losses
    elif phase == "save":
        state, losses = run_steps(state, epoch=0, n=2)
        from moco_tpu.utils.checkpoint import CheckpointManager

        mgr = CheckpointManager(workdir)
        mgr.save(int(state.step), state, extra={"epoch": 0})
        mgr.close()
        # uninterrupted continuation: the oracle the restored pair must hit
        state, post = run_steps(state, epoch=1, n=2)
        evidence["pre_losses"] = losses
        evidence["post_losses"] = post
        losses = losses + post
    else:
        state, losses = run_steps(state, epoch=0, n=2)

    print(
        json.dumps(
            {
                "process": pid,
                "process_count": jax.process_count(),
                "world_devices": len(world),
                "local_devices": len(jax.local_devices()),
                "local_rows": int(part.local_rows),
                "global_batch": batch,
                "local_positions": np.asarray(part.local_positions).tolist(),
                "losses": losses,
                "final_step": int(state.step),
                **evidence,
            }
        ),
        flush=True,
    )


if __name__ == "__main__":
    main()
