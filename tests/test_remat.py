"""remat=True must change memory behavior only — identical numerics."""

import dataclasses

import jax
import pytest
import jax.numpy as jnp
import numpy as np

from moco_tpu.core import build_encoder, create_state, make_train_step, place_state
from moco_tpu.parallel import create_mesh, shard_batch
from moco_tpu.utils.config import DataConfig, MocoConfig, OptimConfig, TrainConfig
from moco_tpu.utils.schedules import build_optimizer


def _one_step(remat: bool):
    config = TrainConfig(
        moco=MocoConfig(
            arch="resnet18", dim=16, num_negatives=32, temperature=0.2,
            mlp=True, shuffle="gather_perm", cifar_stem=True,
            compute_dtype="float32", remat=remat,
        ),
        optim=OptimConfig(lr=0.05, epochs=1),
        data=DataConfig(dataset="synthetic", image_size=16, global_batch=8),
    )
    mesh = create_mesh(num_data=2, num_model=1, devices=jax.devices()[:2])
    encoder = build_encoder(config.moco, num_data=2)
    tx = build_optimizer(config.optim, steps_per_epoch=4)
    state = create_state(jax.random.PRNGKey(0), config, encoder, tx, jnp.zeros((1, 16, 16, 3)))
    state = place_state(state, mesh)
    step = make_train_step(config, encoder, tx, mesh)
    ims = jax.random.normal(jax.random.PRNGKey(1), (2, 8, 16, 16, 3))
    batch = shard_batch(mesh, {"im_q": ims[0], "im_k": ims[1]})
    rng = jax.device_put(
        jax.random.PRNGKey(2), jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec())
    )
    return step(state, batch, rng)


@pytest.mark.slow  # two full train-step compiles back to back
def test_remat_is_numerically_identical():
    s1, m1 = _one_step(remat=False)
    s2, m2 = _one_step(remat=True)
    np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]), rtol=1e-6)
    for a, b in zip(jax.tree.leaves(s1.params_q), jax.tree.leaves(s2.params_q)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7)
