"""Guards on moco_tpu.utils.platform.enable_persistent_compilation_cache.

The persistent XLA compilation cache exists so TPU battery legs and the
driver's end-of-round bench share one compile of the ~3.5-min r50/224
step (PROFILE.md). It must stay OFF for CPU-resolved runs: XLA:CPU's
AOT cache loader warns (and documents a SIGILL hazard) on
machine-feature mismatches between writer and reader processes.
"""

from __future__ import annotations

import os

import jax
import pytest

from moco_tpu.utils.platform import enable_persistent_compilation_cache


@pytest.fixture
def restore_cache_config():
    before = jax.config.jax_compilation_cache_dir
    yield
    jax.config.update("jax_compilation_cache_dir", before)


def test_cpu_backend_skips_cache(restore_cache_config, monkeypatch, tmp_path):
    # conftest pins the CPU platform, so default_backend() == "cpu" here
    monkeypatch.delenv("MOCO_COMPILE_CACHE_DIR", raising=False)
    monkeypatch.delenv("MOCO_NO_COMPILE_CACHE", raising=False)
    jax.config.update("jax_compilation_cache_dir", None)
    enable_persistent_compilation_cache(str(tmp_path / "cache"))
    assert jax.config.jax_compilation_cache_dir is None
    assert not (tmp_path / "cache").exists()


def test_explicit_dir_overrides_cpu_guard(restore_cache_config, monkeypatch, tmp_path):
    target = tmp_path / "explicit"
    monkeypatch.setenv("MOCO_COMPILE_CACHE_DIR", str(target))
    monkeypatch.delenv("MOCO_NO_COMPILE_CACHE", raising=False)
    enable_persistent_compilation_cache()
    assert jax.config.jax_compilation_cache_dir == str(target)
    assert target.is_dir()


def test_opt_out_wins(restore_cache_config, monkeypatch, tmp_path):
    monkeypatch.setenv("MOCO_NO_COMPILE_CACHE", "1")
    monkeypatch.setenv("MOCO_COMPILE_CACHE_DIR", str(tmp_path / "never"))
    jax.config.update("jax_compilation_cache_dir", None)
    enable_persistent_compilation_cache()
    assert jax.config.jax_compilation_cache_dir is None
    assert not (tmp_path / "never").exists()


def test_bn_compile_repro_grid_order():
    """The bisect harness must order each depth's cells baseline-first,
    shipped-slice-suspects last (an abandoned pathological cell forfeits
    the least information — scripts/bn_compile_repro.py docstring)."""
    from conftest import load_script

    mod = load_script("bn_compile_repro.py")
    cells = mod.depth_cells([0, 32, 8], ["mask", "fwd", "barrier", "slice"])
    assert cells[0] == ("slice", 0)
    assert cells[-2:] == [("slice", 32), ("slice", 8)]
    # controls in between, one per (variant, subset-rows) pair
    assert set(cells[1:-2]) == {
        (v, r) for v in ("mask", "fwd", "barrier") for r in (32, 8)
    }
    # no slice: no baseline cell, nothing crashes
    assert mod.depth_cells([0, 32], ["mask"]) == [("mask", 32)]
