"""IVF approximate-NN tier + int8 scoring (ISSUE 9): k-means coarse
quantizer, recall properties vs the exact oracle across fill levels /
shard widths / nprobe settings, freeze discipline per (m, k, nprobe),
incremental FIFO maintenance, engine int8 PTQ, batcher mode routing,
server wiring (mode knob, recall gauge, /ingest), schema validators,
and the perf-ledger ann series gate."""

import json
import time
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from moco_tpu.ops.losses import l2_normalize
from moco_tpu.serve.index import (
    EmbeddingIndex,
    IndexRecompileError,
    kmeans_fit,
)

from tests.conftest import load_script


def _clustered(nc=16, per=32, dim=16, noise=0.2, seed=0):
    """Mixture-of-Gaussians rows on the sphere — the geometry trained
    dictionaries have; uniform rows give any ANN nothing to exploit."""
    rng = np.random.default_rng(seed)
    centers = rng.normal(size=(nc, dim)).astype(np.float32)
    rows = np.repeat(centers, per, axis=0) + noise * rng.normal(
        size=(nc * per, dim)
    ).astype(np.float32)
    rows = np.asarray(l2_normalize(jnp.asarray(rows)))
    order = rng.permutation(rows.shape[0])  # cells must be learned, not given
    return rows[order], centers


def _recall(approx_idx, oracle_idx, k):
    return float(np.mean([
        len(set(approx_idx[i, :k]) & set(oracle_idx[i, :k])) / k
        for i in range(oracle_idx.shape[0])
    ]))


def _queries(rows, m, seed=1, noise=0.05):
    rng = np.random.default_rng(seed)
    q = rows[rng.integers(0, rows.shape[0], m)] + noise * rng.normal(
        size=(m, rows.shape[1])
    ).astype(np.float32)
    return np.asarray(l2_normalize(jnp.asarray(q)))


# -- k-means coarse quantizer --------------------------------------------


def test_kmeans_quantizes_clustered_rows_tightly():
    """Lloyd converges to SOME good partition (local optima may split a
    true cluster and merge two others — that's fine for an IVF coarse
    quantizer): assert the quantization objective, not center recovery.
    Every row must sit in a tight cosine ball of its nearest centroid."""
    rows, _ = _clustered(nc=8, per=64, noise=0.05)
    init = np.asarray(kmeans_fit(jnp.asarray(rows), nlist=8, iters=0))
    cents = np.asarray(kmeans_fit(jnp.asarray(rows), nlist=8, iters=10))
    best = (rows @ cents.T).max(axis=1)
    assert best.mean() > (rows @ init.T).max(axis=1).mean(), "Lloyd didn't improve"
    assert best.mean() > 0.85, best.mean()
    assert best.min() > 0.6, best.min()
    np.testing.assert_allclose(np.linalg.norm(cents, axis=1), 1.0, rtol=1e-5)


def test_kmeans_rejects_nlist_above_rows():
    with pytest.raises(ValueError, match="training rows"):
        kmeans_fit(jnp.zeros((4, 8)), nlist=8)


def test_kmeans_deterministic():
    rows, _ = _clustered(nc=4, per=16)
    a = np.asarray(kmeans_fit(jnp.asarray(rows), nlist=4, iters=5))
    b = np.asarray(kmeans_fit(jnp.asarray(rows), nlist=4, iters=5))
    np.testing.assert_array_equal(a, b)


# -- recall properties vs the exact oracle -------------------------------


@pytest.mark.parametrize("fill", [0.25, 0.6, 1.0])
@pytest.mark.parametrize("nprobe", [4, 8])
def test_ivf_recall_floor_across_fills_and_nprobe(fill, nprobe):
    """The acceptance property: recall@k >= 0.95 vs the exact oracle,
    across fill levels and probe widths (clustered dictionary)."""
    rows, _ = _clustered(nc=16, per=32)
    idx = EmbeddingIndex(rows.shape[0], rows.shape[1])
    n = int(rows.shape[0] * fill)
    idx.snapshot(rows[:n])
    idx.train_ivf(nlist=16, nprobe=nprobe)
    q = _queries(rows[:n], 12)
    _, exact = idx.query(q, 10)
    _, ivf = idx.query(q, 10, mode="ivf")
    assert _recall(ivf, exact, 10) >= 0.95
    assert (ivf < max(n, 10)).all() or n >= 10  # never a junk row


def test_ivf_full_probe_matches_exact():
    """nprobe == nlist with no spill scans every cell: the IVF top-k SET
    equals the exact top-k (scores allclose; order ties aside)."""
    rows, _ = _clustered(nc=8, per=16, dim=8)
    idx = EmbeddingIndex(rows.shape[0], rows.shape[1])
    idx.snapshot(rows)
    stats = idx.train_ivf(nlist=8, nprobe=8)
    assert stats["spilled"] == 0
    q = _queries(rows, 6)
    se, ie = idx.query(q, 5)
    si, ii = idx.query(q, 5, mode="ivf")
    for r in range(q.shape[0]):
        assert set(ie[r]) == set(ii[r])
    np.testing.assert_allclose(np.sort(se, 1), np.sort(si, 1), rtol=1e-5, atol=1e-6)


def test_ivf_sharded_matches_single_device():
    from moco_tpu.parallel import create_mesh

    rows, _ = _clustered(nc=8, per=32, dim=16)
    q = _queries(rows, 8)
    plain = EmbeddingIndex(rows.shape[0], 16)
    plain.snapshot(rows)
    plain.train_ivf(nlist=8, nprobe=4)
    mesh = create_mesh()
    sharded = EmbeddingIndex(rows.shape[0], 16, mesh=mesh)
    sharded.snapshot(rows)
    sharded.train_ivf(nlist=8, nprobe=4)
    s1, i1 = plain.query(q, 5, mode="ivf")
    s2, i2 = sharded.query(q, 5, mode="ivf")
    np.testing.assert_array_equal(i1, i2)
    np.testing.assert_allclose(s1, s2, rtol=1e-5, atol=1e-6)


# -- int8 scoring path ---------------------------------------------------


def test_int8_exact_scores_within_rescale_bounds():
    """Symmetric per-row int8 + f32 rescale: scores within the analytic
    quantization bound of the f32 oracle (|err| <~ 2*sqrt(d)/127 for
    unit rows; empirically far tighter), and int8-IVF recall vs the
    int8-exact oracle stays at the floor (the IVF mechanism itself
    loses nothing extra in int8)."""
    rows, _ = _clustered(nc=16, per=32)
    idx = EmbeddingIndex(rows.shape[0], rows.shape[1])
    idx.snapshot(rows)
    idx.train_ivf(nlist=16, nprobe=8)
    idx.enable_int8()
    q = _queries(rows, 12)
    se, _ = idx.query(q, 10)
    s8, i8e = idx.query(q, 10, mode="exact_i8")
    assert np.abs(s8 - se).max() < 0.02, "int8 rescale error out of bounds"
    _, i8v = idx.query(q, 10, mode="ivf_i8")
    assert _recall(i8v, i8e, 10) >= 0.95


def test_int8_mirror_follows_fifo_ingest():
    rows, _ = _clustered(nc=4, per=16, dim=8)
    idx = EmbeddingIndex(rows.shape[0], 8)
    idx.snapshot(rows)
    idx.enable_int8()
    fresh = _queries(rows, 8, seed=9, noise=0.3)
    idx.add(fresh)
    s, i = idx.query(fresh[:4], 1, mode="exact_i8")
    # the freshly written (requantized-on-device) rows are their own
    # nearest neighbors at the head
    np.testing.assert_array_equal(i[:, 0], np.arange(4))
    assert (s[:, 0] > 0.99).all()


# -- freeze discipline per (m, k, nprobe) --------------------------------


def test_frozen_rejects_unprepared_m_k_nprobe_and_mode():
    rows, _ = _clustered(nc=4, per=16, dim=8)
    idx = EmbeddingIndex(rows.shape[0], 8)
    idx.snapshot(rows)
    idx.train_ivf(nlist=4, nprobe=2)
    idx.enable_int8()
    idx.prepare([4], k=3, nprobe=2, modes=("exact", "ivf"))
    idx.freeze()
    q = _queries(rows, 4)
    idx.query(q, 3)  # prepared
    idx.query(q, 3, mode="ivf", nprobe=2)  # prepared
    for bad in (
        lambda: idx.query(q[:3], 3, mode="ivf", nprobe=2),  # unprepared m
        lambda: idx.query(q, 2, mode="ivf", nprobe=2),  # unprepared k
        lambda: idx.query(q, 3, mode="ivf", nprobe=3),  # unprepared nprobe
        lambda: idx.query(q, 3, mode="ivf_i8", nprobe=2),  # unprepared mode
    ):
        with pytest.raises(IndexRecompileError):
            bad()
    assert idx.recompiles_after_warmup == 0


def test_ivf_modes_require_training_and_int8():
    idx = EmbeddingIndex(16, 8)
    idx.snapshot(np.eye(8, dtype=np.float32))
    with pytest.raises(ValueError, match="train_ivf"):
        idx.query(np.eye(8, dtype=np.float32)[:2], 2, mode="ivf")
    with pytest.raises(ValueError, match="enable_int8"):
        idx.query(np.eye(8, dtype=np.float32)[:2], 2, mode="exact_i8")
    with pytest.raises(ValueError, match="unknown query mode"):
        idx.query(np.eye(8, dtype=np.float32)[:2], 2, mode="cosine")


def test_k_exceeding_candidate_pool_rejected():
    rows, _ = _clustered(nc=4, per=4, dim=8, noise=0.05)
    idx = EmbeddingIndex(rows.shape[0], 8)
    idx.snapshot(rows)
    idx.train_ivf(nlist=4, cell_cap=8, nprobe=1)
    with pytest.raises(ValueError, match="candidate pool"):
        idx.query(_queries(rows, 2), 9, mode="ivf", nprobe=1)


# -- incremental FIFO maintenance ----------------------------------------


def test_ivf_cells_follow_fifo_eviction_and_ingest():
    """After FIFO blocks overwrite old rows, IVF queries find the fresh
    rows and never surface evicted content; cell bookkeeping stays
    consistent (every valid row in exactly one cell or spilled)."""
    rows, centers = _clustered(nc=8, per=16, dim=16, noise=0.1)
    idx = EmbeddingIndex(rows.shape[0], 16)
    idx.snapshot(rows)
    idx.train_ivf(nlist=8, nprobe=8)  # full probe: IVF == exact reachability
    for seed in (3, 4, 5):
        fresh = _queries(rows, 32, seed=seed, noise=0.4)
        idx.add(fresh)
        s, i = idx.query(fresh[:8], 1, mode="ivf")
        start = (idx._ptr - 32) % idx.capacity
        np.testing.assert_array_equal(
            i[:, 0], (start + np.arange(8)) % idx.capacity
        )
        assert (s[:, 0] > 0.999).all()
    ivf = idx._ivf
    in_cells = sorted(x for x in ivf["cells"].flatten() if x < idx.capacity)
    assert len(in_cells) == len(set(in_cells)), "row in two cells"
    assert len(in_cells) + ivf["spilled"] == idx.count
    counts_from_table = (ivf["cells"] < idx.capacity).sum(axis=1)
    np.testing.assert_array_equal(counts_from_table, ivf["counts"])


def test_ivf_add_with_wrap_keeps_recall():
    rows, _ = _clustered(nc=4, per=16, dim=8)
    idx = EmbeddingIndex(rows.shape[0], 8)
    idx.snapshot(rows)
    idx.train_ivf(nlist=4, nprobe=4)
    idx._ptr = idx.capacity - 3  # force the wrap split on the next add
    fresh = _queries(rows, 8, seed=7, noise=0.3)
    idx.add(fresh)
    _, exact = idx.query(fresh, 5)
    _, ivf = idx.query(fresh, 5, mode="ivf")
    assert _recall(ivf, exact, 5) >= 0.95


def test_snapshot_invalidates_trained_ivf():
    rows, _ = _clustered(nc=4, per=8, dim=8)
    idx = EmbeddingIndex(rows.shape[0], 8)
    idx.snapshot(rows)
    idx.train_ivf(nlist=4)
    idx.snapshot(rows[::-1])  # bulk reload: cells are content-derived
    assert idx._ivf is None
    with pytest.raises(ValueError, match="train_ivf"):
        idx.query(rows[:2], 2, mode="ivf")


def test_sharded_add_keeps_sharding_without_host_copy():
    """Satellite 1: the donated jitted fifo_write keeps the P(data)
    sharding in place across add() — no re-shard, same results as the
    single-device index."""
    from moco_tpu.parallel import create_mesh

    mesh = create_mesh()
    rows, _ = _clustered(nc=4, per=16, dim=8)
    sharded = EmbeddingIndex(rows.shape[0], 8, mesh=mesh)
    plain = EmbeddingIndex(rows.shape[0], 8)
    for idx in (sharded, plain):
        idx.snapshot(rows[:32])
    want = sharded.rows.sharding
    fresh = _queries(rows, 16, seed=11)
    for idx in (sharded, plain):
        idx.add(fresh)
    assert sharded.rows.sharding.is_equivalent_to(want, sharded.rows.ndim)
    np.testing.assert_array_equal(np.asarray(sharded.rows), np.asarray(plain.rows))
    s1, i1 = sharded.query(fresh[:4], 3)
    s2, i2 = plain.query(fresh[:4], 3)
    np.testing.assert_array_equal(i1, i2)
    np.testing.assert_allclose(s1, s2, rtol=1e-6, atol=1e-6)


# -- engine int8 PTQ ------------------------------------------------------


def test_quantize_params_roundtrip_bounds():
    from moco_tpu.serve.engine import dequantize_params, quantize_params_int8

    rng = np.random.default_rng(0)
    params = {
        "conv": {"kernel": jnp.asarray(rng.normal(size=(3, 3, 8, 16)), jnp.float32)},
        "dense": {
            "kernel": jnp.asarray(rng.normal(size=(16, 4)), jnp.float32),
            "bias": jnp.asarray(rng.normal(size=(4,)), jnp.float32),
        },
    }
    q, s = quantize_params_int8(params)
    assert q["conv"]["kernel"].dtype == jnp.int8
    assert q["dense"]["kernel"].dtype == jnp.int8
    assert q["dense"]["bias"].dtype == jnp.float32  # 1-D: passes through
    deq = dequantize_params(q, s)
    for path in (("conv", "kernel"), ("dense", "kernel")):
        a = params[path[0]][path[1]]
        b = deq[path[0]][path[1]]
        # symmetric per-output-channel: |err| <= scale/2 = max|w|/254
        bound = np.abs(np.asarray(a)).max(axis=tuple(range(a.ndim - 1))) / 254.0
        assert (np.abs(np.asarray(a - b)) <= bound[None] + 1e-7).all()
    np.testing.assert_array_equal(deq["dense"]["bias"], params["dense"]["bias"])


@pytest.mark.slow
def test_engine_int8_ptq_embeddings_close_and_no_recompiles():
    from moco_tpu.core import build_encoder
    from moco_tpu.serve.engine import InferenceEngine
    from moco_tpu.utils.config import MocoConfig

    cfg = MocoConfig(
        arch="resnet18", dim=16, mlp=True, cifar_stem=True,
        shuffle="none", compute_dtype="float32",
    )
    enc = build_encoder(cfg)
    v = enc.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)), train=False)
    kwargs = dict(image_size=32, buckets=(1, 4))
    f32 = InferenceEngine(enc, v["params"], v.get("batch_stats", {}), **kwargs)
    i8 = InferenceEngine(
        enc, v["params"], v.get("batch_stats", {}), int8=True, **kwargs
    )
    assert i8.int8
    for e in (f32, i8):
        e.warmup()
    imgs = np.random.default_rng(0).integers(0, 255, (4, 32, 32, 3), np.uint8)
    a, _ = f32.embed(imgs)
    b, executed = i8.embed(imgs)
    assert executed == [(4, 4)]
    # weight-only PTQ keeps the representation: near-unit cosine per row
    cos = np.sum(a * b, axis=1)
    assert (cos > 0.99).all(), cos
    np.testing.assert_allclose(np.linalg.norm(b, axis=1), 1.0, rtol=1e-5)
    assert i8.recompiles_after_warmup == 0
    # the at-rest quantized tree really is int8 (the seam's memory win)
    leaves = jax.tree.leaves(i8._qparams)
    i8_bytes = sum(x.nbytes for x in leaves if x.dtype == jnp.int8)
    f32_bytes = sum(x.nbytes for x in jax.tree.leaves(v["params"]))
    assert i8_bytes > 0 and i8_bytes < f32_bytes / 3


# -- batcher mode routing -------------------------------------------------


def test_batcher_passes_modes_to_three_arg_run_batch():
    from moco_tpu.serve.batcher import ContinuousBatcher

    seen = []

    def run_batch(images, want_neighbors, modes):
        seen.append((want_neighbors, modes))
        n = images.shape[0]
        return {"embedding": np.zeros((n, 2), np.float32)}, [(n, n)]

    b = ContinuousBatcher(run_batch, max_batch=4, slo_ms=200)
    try:
        futs = [
            b.submit(np.zeros((1, 4, 4, 3), np.uint8), want_neighbors=True, mode="ivf"),
            b.submit(np.zeros((1, 4, 4, 3), np.uint8), want_neighbors=True),
            b.submit(np.zeros((2, 4, 4, 3), np.uint8), want_neighbors=True, mode="exact"),
        ]
        for f in futs:
            f.result(10)
    finally:
        b.close()
    assert seen and seen[0][0] is True
    assert seen[0][1] == ("exact", "ivf")  # None-mode rider adds nothing


def test_batcher_two_arg_run_batch_still_supported():
    from moco_tpu.serve.batcher import ContinuousBatcher

    def legacy(images, want_neighbors):
        return {"embedding": np.zeros((images.shape[0], 2), np.float32)}, [(1, 1)]

    b = ContinuousBatcher(legacy, max_batch=2, slo_ms=100)
    try:
        out = b.submit(np.zeros((1, 4, 4, 3), np.uint8), mode="ivf").result(10)
        assert out["embedding"].shape == (1, 2)
    finally:
        b.close()


def test_serve_metrics_recall_gauge():
    from moco_tpu.obs import schema
    from moco_tpu.serve.batcher import ServeMetrics

    m = ServeMetrics(slo_ms=100)
    rec = {"step": 1, "time": time.time(), **m.payload()}
    assert rec["serve/recall_estimate"] is None
    assert schema.validate_line(rec) == []
    m.record_recall(1.0)
    m.record_recall(0.9)
    assert abs(m.payload()["serve/recall_estimate"] - 0.95) < 1e-9


# -- schema validators ----------------------------------------------------


def test_schema_serving_tier_validators():
    from moco_tpu.obs import schema

    base = {"step": 1, "time": 0.0}
    good = dict(base, **{
        "serve/recall_estimate": 0.97, "serve/nprobe": 8,
        "serve/int8": 0, "serve/ingested_rows": 128,
    })
    assert schema.validate_line(good) == []
    assert schema.validate_line(dict(base, **{"serve/recall_estimate": 1.5}))
    assert schema.validate_line(dict(base, **{"serve/recall_estimate": -0.1}))
    assert schema.validate_line(dict(base, **{"serve/nprobe": 0}))
    assert schema.validate_line(dict(base, **{"serve/nprobe": 2.5}))
    assert schema.validate_line(dict(base, **{"serve/int8": 2}))
    assert schema.validate_line(dict(base, **{"serve/ingested_rows": None}))
    # nulls allowed where the gauge is dormant
    assert schema.validate_line(dict(base, **{
        "serve/recall_estimate": None, "serve/nprobe": None, "serve/int8": 1,
    })) == []


# -- serve_ingest ---------------------------------------------------------


def test_serve_ingest_fresh_rows_diff():
    si = load_script("serve_ingest.py")
    q = np.arange(8)[:, None] * np.ones((8, 2), np.float32)
    # first sighting: whole queue, oldest-first from the head
    np.testing.assert_array_equal(
        si.fresh_rows(q, None, 3)[:, 0], [3, 4, 5, 6, 7, 0, 1, 2]
    )
    np.testing.assert_array_equal(si.fresh_rows(q, 2, 5)[:, 0], [2, 3, 4])
    np.testing.assert_array_equal(si.fresh_rows(q, 6, 2)[:, 0], [6, 7, 0, 1])
    assert si.fresh_rows(q, 4, 4).shape[0] == 0


# -- perf ledger: the ann series gates like the others --------------------


def test_perf_ledger_gates_ann_series(tmp_path):
    pl = load_script("perf_ledger.py")
    ledger = str(tmp_path / "ledger.json")
    rec = {
        "metric": "moco_v1_r18_cpu_smoke_imgs_per_sec",
        "value": 10.0,
        "ann_ab": {
            "metric": "moco_ann_ivf_cpu_smoke_queries_per_sec",
            "value": 300.0,
            "exact_qps": 40.0,
            "speedup": 7.5,
            "recall_at_10": 0.99,
        },
    }
    cand = str(tmp_path / "bench.json")

    def write(r):
        with open(cand, "w") as f:
            json.dump(r, f)

    write(rec)
    assert pl.check(ledger, cand) == 0  # empty ledger: nothing comparable
    pl.append(ledger, cand, "t01")
    assert pl.load_ledger(ledger)["entries"][0]["ann_ab"]["value"] == 300.0
    assert pl.check(ledger, cand) == 0  # healthy
    # qps regressed beyond the cpu-smoke threshold
    write(dict(rec, ann_ab={**rec["ann_ab"], "value": 100.0}))
    assert pl.check(ledger, cand) == 1
    # qps fine but recall below the floor: a fast-and-wrong index fails
    write(dict(rec, ann_ab={**rec["ann_ab"], "recall_at_10": 0.80}))
    assert pl.check(ledger, cand) == 1
    # old records without an ann block still check cleanly
    write({"metric": rec["metric"], "value": 10.0})
    assert pl.check(ledger, cand) == 0


# -- fused gather-scan tier (ISSUE 11) -----------------------------------


@pytest.mark.parametrize("fill", [0.25, 0.6, 1.0])
@pytest.mark.parametrize("nprobe", [4, 8])
def test_fused_matches_composed_across_fills_and_nprobe(fill, nprobe):
    """The fused oracle property: identical top-k ids (same candidate
    set by construction — distinct probes, one cell per row) and
    allclose scores vs the composed scan, across fill levels and probe
    widths on ties-free clustered data."""
    rows, _ = _clustered(nc=16, per=32)
    idx = EmbeddingIndex(rows.shape[0], rows.shape[1])
    n = int(rows.shape[0] * fill)
    idx.snapshot(rows[:n])
    idx.train_ivf(nlist=16, nprobe=nprobe)
    q = _queries(rows[:n], 12)
    sc, ic = idx.query(q, 10, mode="ivf")
    sf, i_f = idx.query(q, 10, mode="ivf_fused")
    np.testing.assert_array_equal(ic, i_f)
    finite = np.isfinite(sc)
    np.testing.assert_allclose(sf[finite], sc[finite], rtol=1e-5, atol=1e-6)
    np.testing.assert_array_equal(np.isfinite(sf), finite)


def test_fused_int8_matches_composed_int8():
    rows, _ = _clustered(nc=16, per=32)
    idx = EmbeddingIndex(rows.shape[0], rows.shape[1])
    idx.snapshot(rows)
    idx.train_ivf(nlist=16, nprobe=6)
    idx.enable_int8()
    q = _queries(rows, 10)
    sc, ic = idx.query(q, 8, mode="ivf_i8")
    sf, i_f = idx.query(q, 8, mode="ivf_fused_i8")
    np.testing.assert_array_equal(ic, i_f)
    np.testing.assert_allclose(sf, sc, rtol=1e-5, atol=1e-5)


def test_fused_sharded_matches_single_device():
    """Shard-width property: the fused scan over P(data)-sharded rows
    returns exactly the single-device result (same discipline as the
    composed-scan sharding test above)."""
    from moco_tpu.parallel import create_mesh

    rows, _ = _clustered(nc=8, per=32, dim=16)
    q = _queries(rows, 8)
    plain = EmbeddingIndex(rows.shape[0], 16)
    plain.snapshot(rows)
    plain.train_ivf(nlist=8, nprobe=4)
    mesh = create_mesh()
    sharded = EmbeddingIndex(rows.shape[0], 16, mesh=mesh)
    sharded.snapshot(rows)
    sharded.train_ivf(nlist=8, nprobe=4)
    s1, i1 = plain.query(q, 5, mode="ivf_fused")
    s2, i2 = sharded.query(q, 5, mode="ivf_fused")
    np.testing.assert_array_equal(i1, i2)
    np.testing.assert_allclose(s1, s2, rtol=1e-5, atol=1e-6)
    # and the fused result equals the composed result on the mesh too
    s3, i3 = sharded.query(q, 5, mode="ivf")
    np.testing.assert_array_equal(i2, i3)


def test_fused_pallas_interpret_matches_composed(monkeypatch):
    """The Pallas cell-DMA lowering (scalar-prefetched cell tiles from
    the cell-major row copy) in interpret mode returns the composed
    scan's exact ids — the equivalence CI can check without a chip."""
    monkeypatch.setenv("MOCO_IVF_PALLAS", "interpret")
    rows, _ = _clustered(nc=8, per=32, dim=16)
    q = _queries(rows, 6)
    idx = EmbeddingIndex(rows.shape[0], 16)
    assert idx._fused_pallas and idx._fused_interpret
    idx.snapshot(rows)
    idx.train_ivf(nlist=8, nprobe=4)
    sc, ic = idx.query(q, 5, mode="ivf")
    sf, i_f = idx.query(q, 5, mode="ivf_fused")
    np.testing.assert_array_equal(ic, i_f)
    np.testing.assert_allclose(sf, sc, rtol=1e-5, atol=1e-6)


def test_fused_follows_fifo_ingest():
    """Incremental maintenance parity: after FIFO writes re-home cells,
    the fused scan still mirrors the composed scan (the cell-major
    Pallas copy is also invalidated — covered via the dirty flag)."""
    rows, _ = _clustered(nc=8, per=16, dim=8)
    idx = EmbeddingIndex(rows.shape[0], 8)
    idx.snapshot(rows)
    idx.train_ivf(nlist=8, nprobe=4)
    fresh = _queries(rows, 16, seed=9, noise=0.3)
    idx.add(fresh)
    q = _queries(rows, 8, seed=10)
    sc, ic = idx.query(q, 5, mode="ivf")
    sf, i_f = idx.query(q, 5, mode="ivf_fused")
    np.testing.assert_array_equal(ic, i_f)
    np.testing.assert_allclose(sf, sc, rtol=1e-5, atol=1e-6)


def test_frozen_rejects_unprepared_fused_modes():
    rows, _ = _clustered(nc=4, per=16, dim=8)
    idx = EmbeddingIndex(rows.shape[0], 8)
    idx.snapshot(rows)
    idx.train_ivf(nlist=4, nprobe=2)
    idx.enable_int8()
    idx.prepare([4], k=3, modes=("ivf_fused",))
    idx.freeze()
    q = _queries(rows, 4)
    idx.query(q, 3, mode="ivf_fused")  # prepared: fine
    assert idx.recompiles_after_warmup == 0
    with pytest.raises(IndexRecompileError):
        idx.query(q[:2], 3, mode="ivf_fused")  # unprepared m
    with pytest.raises(IndexRecompileError):
        idx.query(q, 3, mode="ivf_fused_i8")  # unprepared quantized twin


def test_ivf_stats_occupancy_gauge():
    rows, _ = _clustered(nc=8, per=16, dim=8)
    idx = EmbeddingIndex(rows.shape[0], 8)
    idx.snapshot(rows)
    stats = idx.train_ivf(nlist=8, nprobe=4)
    assert 0.0 < stats["occupancy"] <= 1.0
    assert stats["occupancy"] == pytest.approx(
        stats["cell_count_mean"] / stats["cell_cap"]
    )


def test_batcher_mode_counts_surface():
    """serve/mode_<tier> counts: explicit riders under their tier,
    default-mode riders under "default"."""
    from moco_tpu.serve.batcher import ContinuousBatcher

    def run_batch(images, want_neighbors, modes=()):
        return {"embedding": np.zeros((images.shape[0], 4), np.float32)}, [
            (images.shape[0], images.shape[0])
        ]

    b = ContinuousBatcher(run_batch, max_batch=8, slo_ms=50.0)
    try:
        imgs = np.zeros((1, 4, 4, 3), np.uint8)
        futs = [b.submit(imgs, want_neighbors=True, mode="ivf_fused")
                for _ in range(3)]
        futs += [b.submit(imgs, want_neighbors=True) for _ in range(2)]
        for f in futs:
            f.result(timeout=10.0)
    finally:
        b.close()
    payload = b.metrics.payload()
    assert payload["serve/mode_ivf_fused"] == 3
    assert payload["serve/mode_default"] == 2
