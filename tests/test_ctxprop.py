"""Trace-context propagation (ISSUE 18): id minting, header
inject/extract round-trips, malformed-input degradation, and the
contract-coverage hook the lint recorder listens on."""

import pytest

from moco_tpu.obs import ctxprop


def test_id_minting_shapes_and_uniqueness():
    tids = {ctxprop.new_trace_id() for _ in range(64)}
    sids = {ctxprop.new_span_id() for _ in range(64)}
    assert len(tids) == 64 and len(sids) == 64
    for t in tids:
        assert len(t) == ctxprop.TRACE_ID_HEX_LEN
        int(t, 16)  # pure hex
    for s in sids:
        assert len(s) == ctxprop.SPAN_ID_HEX_LEN
        int(s, 16)


def test_inject_extract_round_trip():
    ctx = ctxprop.TraceContext(ctxprop.new_trace_id(), ctxprop.new_span_id())
    headers: dict = {}
    ctxprop.inject(headers, ctx)
    assert headers[ctxprop.TRACE_ID_HEADER] == ctx.trace_id
    assert headers[ctxprop.PARENT_SPAN_HEADER] == ctx.span_id
    back = ctxprop.extract(headers)
    assert back is not None
    assert back.trace_id == ctx.trace_id and back.span_id == ctx.span_id


@pytest.mark.parametrize(
    "trace_id",
    [None, "", "zz" * 16, "abc", "a" * 33, "A" * 32 + "g"],
)
def test_parse_rejects_malformed_trace_id(trace_id):
    assert ctxprop.parse(trace_id, "ab" * 8) is None


def test_parse_degrades_malformed_parent_to_parentless():
    tid = ctxprop.new_trace_id()
    ctx = ctxprop.parse(tid, "not-hex")
    assert ctx is not None and ctx.trace_id == tid and ctx.span_id is None
    ctx2 = ctxprop.parse(tid, None)
    assert ctx2 is not None and ctx2.span_id is None


def test_coverage_callback_sees_both_headers():
    seen = []
    ctxprop.set_coverage_callback(seen.append)
    try:
        ctx = ctxprop.TraceContext(ctxprop.new_trace_id(), ctxprop.new_span_id())
        ctxprop.inject({}, ctx)
        ctxprop.parse(ctx.trace_id, ctx.span_id)
        assert ctxprop.TRACE_ID_HEADER in seen
        assert ctxprop.PARENT_SPAN_HEADER in seen
    finally:
        ctxprop.set_coverage_callback(None)
    # cleared: no further recording
    n = len(seen)
    ctxprop.inject({}, ctx)
    assert len(seen) == n


def test_headers_registered_in_contract_registry():
    from moco_tpu.utils import contracts

    assert contracts.TRACE_HEADERS == (
        ctxprop.TRACE_ID_HEADER,
        ctxprop.PARENT_SPAN_HEADER,
    )
    for path in ("/embed", "/neighbors"):
        assert contracts.OPTIONAL_HEADERS[path] == contracts.TRACE_HEADERS
