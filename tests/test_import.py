"""Reference-checkpoint import (moco_tpu/import_torch.py +
import_pretrain.py): the migration path for users bringing trained
`.pth.tar` files (`main_moco.py:~L312-320` save format) into this
framework. Import must be the exact inverse of export — round-trip
bit-identical — and the produced Orbax workdir must feed the probe
surgery directly."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from moco_tpu.core import build_encoder, create_state
from moco_tpu.export import STAGE_SIZES, resnet_to_torchvision
from moco_tpu.import_torch import (
    head_from_torch,
    import_reference_state_dict,
    torchvision_to_resnet,
)
from moco_tpu.utils.config import DataConfig, MocoConfig, OptimConfig, TrainConfig
from moco_tpu.utils.schedules import build_optimizer

ARCH = "resnet18"
DIM = 32
K = 64


@pytest.fixture(scope="module")
def flax_state():
    config = TrainConfig(
        moco=MocoConfig(
            arch=ARCH, dim=DIM, num_negatives=K, mlp=True,
            shuffle="none", compute_dtype="float32",
        ),
        optim=OptimConfig(lr=0.03, epochs=2),
        data=DataConfig(dataset="synthetic", image_size=32, global_batch=8),
    )
    encoder = build_encoder(config.moco)
    state = create_state(
        jax.random.PRNGKey(7), config, encoder, tx=build_optimizer(config.optim, 4),
        sample_input=jnp.zeros((1, 224, 224, 3)),
    )
    return config, encoder, state


def _torch_style_dict(state):
    """Build a reference-format state dict FROM our trees via the export
    path (backbone) + manual head/queue, prefixed like a DDP save."""
    sd = {}
    for enc, params, stats in (
        ("module.encoder_q.", state.params_q, state.batch_stats_q),
        ("module.encoder_k.", state.params_k, state.batch_stats_k),
    ):
        back = resnet_to_torchvision(
            params["backbone"], stats["backbone"], STAGE_SIZES[ARCH]
        )
        for k, v in back.items():
            sd[enc + k] = v
        head = params["head"]
        sd[enc + "fc.0.weight"] = np.asarray(head["Dense_0"]["kernel"]).T
        sd[enc + "fc.0.bias"] = np.asarray(head["Dense_0"]["bias"])
        sd[enc + "fc.2.weight"] = np.asarray(head["Dense_1"]["kernel"]).T
        sd[enc + "fc.2.bias"] = np.asarray(head["Dense_1"]["bias"])
    sd["module.queue"] = np.asarray(state.queue).T  # reference: (dim, K)
    sd["module.queue_ptr"] = np.asarray([7], np.int64)
    return sd


def _assert_trees_equal(a, b):
    ja, jb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(ja) == len(jb)
    for x, y in zip(ja, jb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_backbone_roundtrip_exact(flax_state):
    _, _, state = flax_state
    sd = resnet_to_torchvision(
        state.params_q["backbone"], state.batch_stats_q["backbone"], STAGE_SIZES[ARCH]
    )
    params, stats = torchvision_to_resnet(sd, STAGE_SIZES[ARCH])
    _assert_trees_equal(params, state.params_q["backbone"])
    _assert_trees_equal(stats, state.batch_stats_q["backbone"])


def test_full_reference_dict_import(flax_state):
    _, _, state = flax_state
    sd = _torch_style_dict(state)
    pieces = import_reference_state_dict(sd, ARCH)
    assert pieces["mlp"] and pieces["dim"] == DIM
    _assert_trees_equal(pieces["params_q"], state.params_q)
    _assert_trees_equal(pieces["params_k"], state.params_k)
    _assert_trees_equal(pieces["batch_stats_q"], state.batch_stats_q)
    np.testing.assert_array_equal(pieces["queue"], np.asarray(state.queue))
    assert pieces["queue_ptr"] == 7


def test_import_forward_parity(flax_state):
    """Imported params must produce the SAME features as the originals —
    the end-to-end guarantee a migrating user cares about."""
    config, encoder, state = flax_state
    pieces = import_reference_state_dict(_torch_style_dict(state), ARCH)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 32, 3))
    want = encoder.apply(
        {"params": state.params_q, "batch_stats": state.batch_stats_q}, x, train=False
    )
    got = encoder.apply(
        {"params": pieces["params_q"], "batch_stats": pieces["batch_stats_q"]},
        x,
        train=False,
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-6)


def test_import_cli_produces_probeable_checkpoint(flax_state, tmp_path, monkeypatch):
    """import_pretrain.py end-to-end: torch .pth.tar -> Orbax workdir ->
    load_pretrained_backbone surgery, params intact."""
    import sys

    import torch

    import import_pretrain
    from moco_tpu.lincls import load_pretrained_backbone

    _, _, state = flax_state
    sd = {
        k: torch.from_numpy(np.ascontiguousarray(np.asarray(v)))
        for k, v in _torch_style_dict(state).items()
    }
    blob = {"epoch": 3, "arch": ARCH, "state_dict": sd}
    pth = tmp_path / "checkpoint_0002.pth.tar"
    torch.save(blob, pth)

    workdir = tmp_path / "imported"
    monkeypatch.setattr(
        sys, "argv",
        ["import_pretrain.py", str(pth), str(workdir), "--steps-per-epoch", "4"],
    )
    import_pretrain.main()

    params, stats, cfg = load_pretrained_backbone(str(workdir))
    assert cfg.moco.arch == ARCH and cfg.moco.mlp and cfg.moco.dim == DIM
    assert cfg.moco.num_negatives == K
    _assert_trees_equal(params, state.params_q["backbone"])
    _assert_trees_equal(stats, state.batch_stats_q["backbone"])


def test_vit_timm_roundtrip_exact():
    """timm_to_vit must invert vit_to_timm bit-exactly (minus pos_embed,
    which is fixed sincos recomputed by the module)."""
    from moco_tpu.export import vit_to_timm
    from moco_tpu.import_torch import timm_to_vit
    from moco_tpu.models.vit import create_vit

    m = create_vit("vit_tiny", image_size=32, patch_size=4)
    params = m.init(jax.random.PRNGKey(5), jnp.zeros((1, 32, 32, 3)), train=False)[
        "params"
    ]
    sd = vit_to_timm(params, patch_size=4, image_size=32)
    back = timm_to_vit(sd, num_heads=3)
    _assert_trees_equal(back, params)

    # and the imported params drive the SAME forward
    x = jax.random.normal(jax.random.PRNGKey(6), (2, 32, 32, 3))
    np.testing.assert_allclose(
        np.asarray(m.apply({"params": back}, x, train=False)),
        np.asarray(m.apply({"params": params}, x, train=False)),
        rtol=1e-6,
    )


def test_v1_head_and_nonddp_prefix(flax_state):
    """v1 checkpoints (single-Linear fc, no MLP) and single-GPU saves
    (no `module.` DDP prefix) must both import."""
    _, _, state = flax_state
    sd = {}
    back = resnet_to_torchvision(
        state.params_q["backbone"], state.batch_stats_q["backbone"], STAGE_SIZES[ARCH]
    )
    for k, v in back.items():
        sd["encoder_q." + k] = v  # non-DDP prefix
    head = state.params_q["head"]
    # v1-style: a single fc (reuse Dense_0's shapes as the linear head)
    sd["encoder_q.fc.weight"] = np.asarray(head["Dense_0"]["kernel"]).T
    sd["encoder_q.fc.bias"] = np.asarray(head["Dense_0"]["bias"])

    pieces = import_reference_state_dict(sd, ARCH)
    assert not pieces["mlp"]
    assert pieces["dim"] == sd["encoder_q.fc.weight"].shape[0]
    assert "params_k" not in pieces  # partial save: only q present
    _assert_trees_equal(
        pieces["params_q"]["backbone"], state.params_q["backbone"]
    )
    np.testing.assert_array_equal(
        pieces["params_q"]["head"]["Dense_0"]["kernel"],
        np.asarray(head["Dense_0"]["kernel"]),
    )
