"""mocolint v4: cross-artifact contract analysis (JX015-JX018).

Covers the contract registry's extraction (metric emissions/validator
tables, handler + client routes, fault hook sites and spec literals),
the declared registry in utils/contracts.py, the runtime
contract-coverage recorder (callbacks into obs/schema + utils/faults,
merge, the newly-dead-contract gate), the SARIF/--dump-contracts CLI
arms, and — via literal `slow@site=` specs — that every registered
serve stage's fault hook is actually exercised (what JX017 clause 3
counts as coverage).
"""

import json
import os

import pytest

from moco_tpu.analysis import contracts
from moco_tpu.analysis.__main__ import main as mocolint_main
from moco_tpu.analysis.engine import analyze_paths, parse_module, render_sarif
from moco_tpu.obs import schema
from moco_tpu.utils import contracts as decl
from moco_tpu.utils import faults

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)), "fixtures", "lint")


def _registry(src: str, path: str = "m/mod.py"):
    ctx = parse_module(src, path)
    assert hasattr(ctx, "tree"), f"parse failed: {ctx}"
    return contracts.build_registry({path: ctx})


# ---------------------------------------------------------------------------
# the declared registry (utils/contracts.py)


def test_declared_registry_shape():
    assert decl.EXIT_CODES == {"stall": 42, "rescale": 75, "kill": 113}
    assert decl.SERVE_PORT_STRIDE == 16
    # /ingest appends rows: a retried ingest double-writes, so it MUST
    # stay outside the idempotent set the router may retry/hedge
    assert decl.ROUTES["/ingest"].idempotent is False
    assert "/ingest" not in decl.IDEMPOTENT_ROUTES
    assert "/embed" in decl.IDEMPOTENT_ROUTES
    assert decl.ROUTES["/embed"].headers == ("X-Image-Shape",)
    assert decl.ROUTES["/ingest"].headers == ("X-Rows-Shape",)
    assert decl.ROUTES["/healthz"].methods == ("GET",)
    for site in decl.SERVE_STAGE_SITES:
        assert site in decl.FAULT_SITES["slow"]


def test_declared_route_gates_server_scope():
    every = contracts.declared_route_gates()
    replica = contracts.declared_route_gates("replica")
    router = contracts.declared_route_gates("router")
    assert "POST /ingest" in replica and "POST /ingest" not in router
    assert "POST /admin/undrain" in router and "POST /admin/undrain" not in replica
    assert "GET /healthz" in replica and "GET /healthz" in router
    # the Prometheus scrape endpoint belongs to neither serve surface
    assert "GET /metrics" in every
    assert "GET /metrics" not in replica and "GET /metrics" not in router
    assert set(replica) | set(router) <= set(every)


# ---------------------------------------------------------------------------
# fault-spec parsing


def test_parse_fault_specs():
    specs = contracts.parse_fault_specs(
        "slow@site=serve.ingress:ms=5,kill@replica=1:at=5 then io@site=data.read:at=2"
    )
    assert [s["kind"] for s in specs] == ["slow", "kill", "io"]
    assert specs[0]["params"] == {"site": "serve.ingress", "ms": "5"}
    assert specs[1]["params"] == {"replica": "1", "at": "5"}
    assert specs[2]["params"]["site"] == "data.read"


def test_parse_fault_specs_fstring_placeholder_site_is_dynamic():
    import ast

    node = ast.parse('f"slow@site={site}:ms=3"').body[0].value
    (spec,) = contracts.parse_fault_specs(contracts._joined_literal(node))
    assert spec["kind"] == "slow"
    assert spec["params"]["site"] is None  # dynamic — unverifiable


# ---------------------------------------------------------------------------
# static registry extraction


def test_registry_extracts_metric_emissions_and_validators():
    reg = _registry(
        "FIELD_VALIDATORS = {'train/loss': None}\n"
        "PREFIX_VALIDATORS = {'train/': None}\n"
        "def flush(sink, group, lr):\n"
        "    payload = {'queue/depth': 3}\n"
        "    payload[f'train/lr_{group}'] = lr\n"
        "    sink.write(payload)\n"
    )
    assert reg.validator_keys() == {"train/loss"}
    assert reg.validator_prefixes() == {"train/"}
    # validator-table dict keys are NOT emissions
    assert {e.key for e in reg.emitted_keys} == {"queue/depth"}
    assert {e.prefix for e in reg.emitted_prefixes} == {"train/lr_"}


def test_registry_extracts_handler_and_client_sides():
    reg = _registry(
        "import urllib.request\n"
        "class H:\n"
        "    def do_GET(self):\n"
        "        if self.path.split('?')[0] == '/healthz':\n"
        "            self.send_response(200)\n"
        "    def do_POST(self):\n"
        "        if self.path in ('/embed', '/neighbors'):\n"
        "            shape = self.headers.get('X-Image-Shape')\n"
        "def probe(base):\n"
        "    return urllib.request.urlopen(base + '/stats', timeout=5)\n"
        "def push(base, body):\n"
        "    return urllib.request.Request(\n"
        "        'http://127.0.0.1:8000/ingest?block=1', data=body)\n"
    )
    handled = {(h.route, h.method) for h in reg.handler_routes}
    assert handled == {
        ("/healthz", "GET"), ("/embed", "POST"), ("/neighbors", "POST")
    }
    assert "X-Image-Shape" in reg.class_headers["m/mod.py::H"]
    calls = {(c.route, c.method) for c in reg.client_calls}
    # full URLs reduce to the path, query strings are stripped, and a
    # non-None data= flips the inferred method to POST
    assert calls == {("/stats", "GET"), ("/ingest", "POST")}
    assert [s.code for s in reg.handler_status] == [200]


def test_registry_extracts_hooks_retry_guards_and_specs():
    reg = _registry(
        "from moco_tpu.utils import faults\n"
        "SITE = 'serve.scatter'\n"
        "def go(retry_call, path, batch):\n"
        "    faults.maybe_slow(SITE)\n"
        "    faults.maybe_delay('data.read')\n"
        "    if path not in ('/embed', '/neighbors'):\n"
        "        return None\n"
        "    return retry_call(lambda: batch)\n"
        "CHAOS = 'slow@site=serve.scatter:ms=9'\n"
    )
    # literal args AND module-level string constants resolve
    assert reg.hook_site_set("slow") == {"serve.scatter"}
    assert reg.hook_site_set("delay") == {"data.read"}
    (wrap,) = reg.retry_wraps
    assert set(wrap.routes) == {"/embed", "/neighbors"}
    (spec,) = reg.spec_literals
    assert spec.kind == "slow" and spec.params["site"] == "serve.scatter"


def test_registry_for_caches_on_program():
    path = os.path.join(FIXTURES, "jx017_good.py")
    findings = analyze_paths([path], rules=["JX017"])
    assert findings == []


# ---------------------------------------------------------------------------
# runtime recorder


def test_recorder_counts_normalize_and_merge():
    rec = contracts.ContractCoverageRecorder()
    rec.record_route("post", "/embed?k=3")
    rec.record_route("POST", "/embed")
    rec.record_validator("serve/p99_ms")
    rec.record_fault_hook("slow", "serve.ingress")
    rec.record_fault_hook("kill", None)
    snap = rec.snapshot()
    assert snap["routes"] == {"POST /embed": 2}
    assert snap["fault_hooks"] == {"slow@serve.ingress": 1, "kill": 1}
    merged = contracts.merge_coverage(
        [snap, {"routes": {"POST /embed": 1, "GET /stats": 4}}]
    )
    assert merged["routes"] == {"POST /embed": 3, "GET /stats": 4}
    assert merged["fault_hooks"]["slow@serve.ingress"] == 1


def test_recorder_dump_roundtrip(tmp_path):
    rec = contracts.ContractCoverageRecorder()
    rec.record_route("GET", "/healthz")
    out = tmp_path / "contract_coverage.json"
    dumped = rec.dump(str(out))
    assert json.loads(out.read_text()) == dumped


def test_check_coverage_flags_seeded_dead_contract():
    """The CI gate's core: a registered contract nothing fired is named
    in the missing list — here /debug/flight is deliberately dead."""
    rec = contracts.ContractCoverageRecorder()
    rec.record_route("GET", "/healthz")
    for site in decl.SERVE_STAGE_SITES:
        rec.record_fault_hook("slow", site)
    missing = contracts.check_coverage(
        rec.snapshot(),
        routes=["GET /healthz", "GET /debug/flight"],
        fault_sites=[f"slow@{s}" for s in decl.SERVE_STAGE_SITES],
        validators=[],
    )
    assert missing == ["route never handled: GET /debug/flight"]


def test_install_recorder_wires_schema_and_faults_callbacks():
    rec = contracts.install_recorder()
    try:
        line = {
            "step": 1,
            "time": 0.0,
            "rescale/dead_hosts": [3],
            "serve/burn_rate_60s": 0.25,
        }
        assert schema.validate_line(line) == []
        faults.maybe_slow("serve.ingress")  # no plan installed: still recorded
        snap = rec.snapshot()
        assert snap["validators"]["rescale/dead_hosts"] == 1
        # the WINNING (longest-match) prefix family is recorded, not the
        # generic serve/ fallback
        assert snap["validators"]["serve/burn_rate_"] == 1
        assert "serve/" not in snap["validators"]
        assert snap["fault_hooks"]["slow@serve.ingress"] == 1
    finally:
        contracts.uninstall_recorder()
    assert contracts.get_recorder() is None


def test_record_route_is_noop_without_recorder():
    contracts.record_route("GET", "/healthz")  # must not raise
    assert contracts.get_recorder() is None


def test_maybe_install_from_env(monkeypatch):
    monkeypatch.delenv("MOCO_CONTRACT_COVERAGE", raising=False)
    assert contracts.maybe_install_from_env() is None
    monkeypatch.setenv("MOCO_CONTRACT_COVERAGE", "1")
    rec = contracts.maybe_install_from_env()
    try:
        assert rec is not None and contracts.get_recorder() is rec
    finally:
        contracts.uninstall_recorder()


# ---------------------------------------------------------------------------
# every registered serve stage's slow hook is exercised (JX017 clause 3
# counts exactly these literal spec strings as coverage — keep them
# literal, an f-string site would parse as dynamic)

SLOW_SITE_SPECS = (
    "slow@site=serve.ingress:ms=1",
    "slow@site=serve.batch_assemble:ms=1",
    "slow@site=serve.engine_execute:ms=1",
    "slow@site=serve.index_query:ms=1",
    "slow@site=serve.scatter:ms=1",
    "slow@site=serve.respond:ms=1",
)


@pytest.mark.parametrize("spec", SLOW_SITE_SPECS)
def test_registered_slow_site_spec_fires_its_hook(spec, monkeypatch):
    site = spec.split("site=")[1].split(":")[0]
    assert site in decl.SERVE_STAGE_SITES
    slept = []
    monkeypatch.setattr(faults.time, "sleep", lambda s: slept.append(s))
    faults.install(spec)
    try:
        faults.maybe_slow(site)
    finally:
        faults.clear()
    assert slept == [0.001]


def test_slow_spec_on_other_site_is_a_noop(monkeypatch):
    slept = []
    monkeypatch.setattr(faults.time, "sleep", lambda s: slept.append(s))
    faults.install("slow@site=serve.ingress:ms=1")
    try:
        faults.maybe_slow("serve.respond")
    finally:
        faults.clear()
    assert slept == []


# ---------------------------------------------------------------------------
# coverage callbacks fire plan-or-no-plan


def test_faults_coverage_callback_fires_without_plan():
    seen = []
    faults.set_coverage_callback(lambda kind, site: seen.append((kind, site)))
    try:
        faults.clear()
        faults.maybe_delay("data.read")
        faults.maybe_io_error("data.read")
        faults.maybe_slow("serve.engine_execute")
    finally:
        faults.set_coverage_callback(None)
    assert ("delay", "data.read") in seen
    assert ("io", "data.read") in seen
    assert ("slow", "serve.engine_execute") in seen


# ---------------------------------------------------------------------------
# CLI arms: SARIF + contract dump + partial-tree stability


def test_render_sarif_structure():
    path = os.path.join(FIXTURES, "jx018_bad.py")
    findings = analyze_paths([path], rules=["JX018"])
    assert findings
    doc = json.loads(render_sarif(findings))
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    rule_ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
    assert {"JX015", "JX016", "JX017", "JX018"} <= rule_ids
    assert run["results"] and all(
        r["ruleId"] == "JX018" for r in run["results"]
    )
    loc = run["results"][0]["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"].endswith("jx018_bad.py")
    assert loc["region"]["startLine"] > 0
    assert "suppressions" not in run["results"][0]  # active finding


def test_render_sarif_marks_suppressed_and_baselined():
    import dataclasses

    from moco_tpu.analysis.engine import Finding

    doc = json.loads(render_sarif([
        Finding("JX018", "m", "a.py", 3, suppressed=True),
        Finding("JX018", "m", "b.py", 4, baselined=True),
    ]))
    results = doc["runs"][0]["results"]
    assert results[0]["suppressions"][0]["kind"] == "inSource"
    assert results[1]["suppressions"][0]["kind"] == "external"
    assert dataclasses.is_dataclass(Finding)


def test_cli_sarif_flag(tmp_path, capsys):
    out = tmp_path / "mocolint.sarif"
    rc = mocolint_main([
        os.path.join(FIXTURES, "jx016_bad.py"),
        "--no-baseline", "--rules", "JX016", "--sarif", str(out),
    ])
    capsys.readouterr()
    assert rc == 1
    doc = json.loads(out.read_text())
    assert doc["runs"][0]["results"]


def test_cli_dump_contracts(tmp_path, capsys):
    out = tmp_path / "contracts.json"
    rc = mocolint_main([
        os.path.join(REPO, "moco_tpu", "serve", "server.py"),
        "--no-baseline", "--rules", "JX018", "--dump-contracts", str(out),
    ])
    capsys.readouterr()
    assert rc == 0
    dumped = json.loads(out.read_text())
    handled = {(h["route"], h["method"]) for h in dumped["handler_routes"]}
    assert ("/embed", "POST") in handled and ("/healthz", "GET") in handled
    assert {h["site"] for h in dumped["hook_sites"] if h["kind"] == "slow"} == {
        "serve.ingress", "serve.respond",
    }


def test_partial_tree_lint_is_quiet_on_fleet_subset(capsys):
    """The fleet smoke lints a 5-file subset with --no-baseline: the v4
    rules must validate against the DECLARED registry there and stay
    quiet (whole-tree-only clauses gated off), or the smoke's lint gate
    would false-positive on every partial run."""
    rc = mocolint_main([
        os.path.join(REPO, "moco_tpu", "serve", "router.py"),
        os.path.join(REPO, "moco_tpu", "serve", "fleet.py"),
        os.path.join(REPO, "moco_tpu", "serve", "replica_main.py"),
        os.path.join(REPO, "moco_tpu", "serve", "batcher.py"),
        os.path.join(REPO, "scripts", "fleet_serve_smoke.py"),
        "--no-baseline",
    ])
    out = capsys.readouterr().out
    assert rc == 0, out
