"""Serving fleet front door (ISSUE 16): the circuit-breaker state
machine, health/load-aware dispatch with replica attribution, retry
failover past a dead replica, tail-latency hedging (first success
wins), load shedding at the in-flight budget, graceful drain/undrain
under live traffic, the batcher + server drain paths, the
`kill@replica` fault grammar, the ReplicaSupervisor's crash-respawn +
warm-replay loop (against a stdlib-only fake replica process), and the
`serve_ingest --fanout` discovery/ingest path.
"""

import http.server
import json
import os
import sys
import textwrap
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from moco_tpu.obs import ctxprop
from moco_tpu.serve.batcher import BatcherClosedError, ContinuousBatcher
from moco_tpu.serve.fleet import ReplicaSupervisor, free_port
from moco_tpu.serve.router import (
    BREAKER_CLOSED,
    BREAKER_HALF_OPEN,
    BREAKER_OPEN,
    CircuitBreaker,
    FleetRouter,
)
from moco_tpu.utils import faults, retry

from tests.conftest import load_script


@pytest.fixture(autouse=True)
def _clean_fault_plan():
    faults.clear()
    yield
    faults.clear()


# -- circuit breaker -----------------------------------------------------


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def test_breaker_trips_after_consecutive_failures():
    clk = _Clock()
    b = CircuitBreaker(fail_threshold=3, cooldown_s=2.0, now=clk)
    assert b.state == BREAKER_CLOSED and b.try_acquire()
    b.record_failure()
    b.record_failure()
    assert b.state == BREAKER_CLOSED  # not yet: needs 3 consecutive
    b.record_success()
    b.record_failure()
    b.record_failure()
    assert b.state == BREAKER_CLOSED  # the success reset the streak
    b.record_failure()
    assert b.state == BREAKER_OPEN and b.trips == 1
    assert not b.try_acquire()  # open: nothing dispatches


def test_breaker_half_open_single_probe_and_recovery():
    clk = _Clock()
    b = CircuitBreaker(fail_threshold=1, cooldown_s=2.0, now=clk)
    b.record_failure()
    assert b.state == BREAKER_OPEN
    clk.t = 1.9
    assert not b.try_acquire()  # still cooling down
    clk.t = 2.1
    assert b.try_acquire()  # the single half-open probe
    assert b.state == BREAKER_HALF_OPEN
    assert not b.try_acquire()  # a second caller is NOT admitted
    b.record_success()
    assert b.state == BREAKER_CLOSED
    assert b.try_acquire() and b.try_acquire()  # closed again: all flow


def test_breaker_failed_probe_retrips_with_exponential_cooldown():
    clk = _Clock()
    b = CircuitBreaker(fail_threshold=1, cooldown_s=2.0, cooldown_cap_s=30.0, now=clk)
    b.record_failure()  # trip 1: cooldown 2s
    clk.t = 2.5
    assert b.try_acquire()
    b.record_failure()  # probe failed -> trip 2: cooldown 4s
    assert b.state == BREAKER_OPEN and b.trips == 2
    clk.t = 2.5 + 3.9
    assert not b.try_acquire()
    clk.t = 2.5 + 4.1
    assert b.try_acquire()
    b.record_success()  # recovery resets the streak
    b.record_failure()  # trip 3 after recovery: back to the base 2s
    clk.t += 2.1
    assert b.try_acquire()


def test_breaker_stale_success_does_not_close_open():
    b = CircuitBreaker(fail_threshold=1, now=_Clock())
    b.record_failure()
    b.record_success()  # a straggler from before the trip
    assert b.state == BREAKER_OPEN


# -- fake replica (in-process HTTP server with the ServeServer API) ------


class FakeReplica:
    """Replica-shaped stdlib HTTP server: /healthz, /stats, /embed,
    /neighbors (replica-scoped request ids), /ingest, /admin/drain —
    with injectable latency and fail-next-N knobs. All mutable state is
    guarded by one lock (handler threads race the test thread)."""

    def __init__(self, index: int, latency_s: float = 0.0):
        self.index = index
        self._lock = threading.Lock()
        self.latency_s = latency_s
        self.fail_next = 0
        self.requests = 0
        self.traced = 0
        self.ingested = 0
        self.ingest_ckpt_step = None
        self.draining = False
        self.stats_extra: dict = {}
        outer = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802
                path = self.path.split("?")[0]
                if path == "/healthz":
                    with outer._lock:
                        draining = outer.draining
                    self._json(200, {
                        "ok": not draining, "warm": True,
                        "draining": draining, "replica": outer.index,
                    })
                elif path == "/stats":
                    with outer._lock:
                        st = {"serve/requests": outer.requests, **outer.stats_extra}
                    self._json(200, st)
                else:
                    self.send_error(404)

            def do_POST(self):  # noqa: N802
                path = self.path.split("?")[0]
                body = self.rfile.read(int(self.headers.get("Content-Length", 0)))
                if path in ("/embed", "/neighbors"):
                    t_wall0 = time.time()
                    t0 = time.perf_counter()
                    with outer._lock:
                        outer.requests += 1
                        seq = outer.requests
                        fail = outer.fail_next > 0
                        if fail:
                            outer.fail_next -= 1
                        latency = outer.latency_s
                    if fail:
                        self.send_error(500)
                        return
                    if latency:
                        time.sleep(latency)
                    rid = f"r{outer.index}-{seq:06d}"
                    out = {"request_id": rid, "rows": 0, "embeddings": []}
                    # in-band trace echo, like ServeServer: a propagated
                    # context comes back as the replica-side waterfall
                    trace_id = self.headers.get("X-Trace-Id")
                    parent = self.headers.get("X-Parent-Span")
                    if trace_id:
                        with outer._lock:
                            outer.traced += 1
                        dt = (time.perf_counter() - t0) * 1e3
                        out["trace"] = {
                            "request_id": rid, "replica": outer.index,
                            "rows": 0, "wall_t0": t_wall0,
                            "total_ms": round(dt, 3),
                            "trace_id": trace_id,
                            "span_id": ctxprop.new_span_id(),
                            "parent_span": parent,
                            "stages": [{
                                "stage": "engine_execute",
                                "start_ms": 0.0, "dur_ms": round(dt, 3),
                            }],
                        }
                    self._json(200, out)
                elif path == "/ingest":
                    shape = self.headers.get("X-Rows-Shape", "0,0").split(",")
                    ckpt_step = self.headers.get("X-Ckpt-Step")
                    with outer._lock:
                        outer.ingested += int(shape[0])
                        n = outer.ingested
                        if ckpt_step is not None:
                            outer.ingest_ckpt_step = int(ckpt_step)
                    self._json(200, {"index_rows": n, "ingested_rows": n})
                elif path == "/admin/drain":
                    with outer._lock:
                        outer.draining = True
                    self._json(200, {"draining": True, "drained": True})
                else:
                    self.send_error(404)

            def _json(self, code, obj):
                payload = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def log_message(self, *a):
                pass

        self.server = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.port = self.server.server_address[1]
        self.url = f"http://127.0.0.1:{self.port}"
        self.thread = threading.Thread(
            target=self.server.serve_forever, name=f"fake_replica_{index}", daemon=True
        )
        self.thread.start()

    def set(self, **kv):
        with self._lock:
            for k, v in kv.items():
                setattr(self, k, v)

    def count(self, name: str) -> int:
        with self._lock:
            return getattr(self, name)

    def close(self):
        self.server.shutdown()
        self.server.server_close()
        self.thread.join(timeout=5)


def _post(url: str, path: str = "/embed", body: bytes = b"x", timeout: float = 30.0):
    req = urllib.request.Request(url + path, data=body)
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def _get(url: str, path: str, timeout: float = 10.0):
    with urllib.request.urlopen(url + path, timeout=timeout) as r:
        return json.loads(r.read())


@pytest.fixture()
def fleet():
    """(router, fakes) — two fake replicas behind a fast-polling
    router; hedging off by default (tests opt in per-router)."""
    fakes = [FakeReplica(0), FakeReplica(1)]
    router = FleetRouter(
        replica_urls=[f.url for f in fakes],
        slo_ms=1000.0,
        health_interval_s=0.1,
        retry_attempts=3,
        retry_base_delay_s=0.01,
        retry_max_delay_s=0.05,
        hedge=False,
        breaker_fail_threshold=2,
        breaker_cooldown_s=0.2,
        drain_timeout_s=5.0,
    )
    try:
        yield router, fakes
    finally:
        router.close()
        for f in fakes:
            f.close()


# -- dispatch ------------------------------------------------------------


def test_router_dispatches_and_attributes_replica(fleet):
    router, fakes = fleet
    url = f"http://127.0.0.1:{router.port}"
    seen = set()
    for _ in range(8):
        status, body = _post(url)
        assert status == 200
        # the response carries BOTH the replica-scoped request id the
        # replica minted and the router's replica attribution, agreeing
        assert body["request_id"].startswith(f"r{body['replica']}-")
        seen.add(body["replica"])
    # least-loaded dispatch over two idle replicas alternates: both serve
    assert seen == {0, 1}
    assert fakes[0].count("requests") + fakes[1].count("requests") == 8
    h = _get(url, "/healthz")
    assert h["ok"] and h["replicas_healthy"] == 2


def test_router_retries_past_dead_replica_and_trips_breaker(fleet):
    router, fakes = fleet
    url = f"http://127.0.0.1:{router.port}"
    retry.snapshot(reset=True)
    fakes[0].set(fail_next=100)  # replica 0 answers 500 to everything
    for _ in range(8):
        status, body = _post(url)
        assert status == 200
        assert body["replica"] == 1  # every request lands on the survivor
    stats = router.stats()
    assert stats["fleet_serve/breaker_trips"] >= 1
    assert stats["fleet_serve/retries"] >= 1
    assert stats["fleet_serve/failed"] == 0
    snaps = _get(url, "/admin/replicas")["replicas"]
    assert {s["index"] for s in snaps} == {0, 1}
    assert any(s["breaker"] == BREAKER_OPEN for s in snaps if s["index"] == 0)


def test_router_breaker_recovers_via_half_open_probe(fleet):
    router, fakes = fleet
    url = f"http://127.0.0.1:{router.port}"
    fakes[0].set(fail_next=100)
    for _ in range(6):
        _post(url)
    assert router.stats()["fleet_serve/breaker_open"] == 1
    fakes[0].set(fail_next=0)  # replica 0 heals
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline:
        _post(url)
        if router.stats()["fleet_serve/breaker_open"] == 0:
            break
        time.sleep(0.05)
    assert router.stats()["fleet_serve/breaker_open"] == 0
    # and it takes traffic again
    before = fakes[0].count("requests")
    for _ in range(10):
        _post(url)
    assert fakes[0].count("requests") > before


# -- hedging -------------------------------------------------------------


def test_hedge_first_winner_beats_slow_primary():
    fakes = [FakeReplica(0, latency_s=1.5), FakeReplica(1)]
    router = FleetRouter(
        replica_urls=[f.url for f in fakes],
        slo_ms=1000.0,
        health_interval_s=0.1,
        hedge=True,
        hedge_min_ms=100.0,
        retry_base_delay_s=0.01,
    )
    url = f"http://127.0.0.1:{router.port}"
    try:
        t0 = time.perf_counter()
        status, body = _post(url)
        elapsed = time.perf_counter() - t0
        assert status == 200
        # the hedge (replica 1, fast) won; the slow primary was discarded
        assert body["replica"] == 1
        assert elapsed < 1.2, f"hedge did not shortcut the slow primary ({elapsed:.2f}s)"
        stats = router.stats()
        assert stats["fleet_serve/hedges"] >= 1
        assert stats["fleet_serve/hedge_wins"] >= 1
    finally:
        router.close()
        for f in fakes:
            f.close()


# -- load shedding -------------------------------------------------------


def test_shed_past_inflight_budget_is_loud_503():
    fakes = [FakeReplica(0, latency_s=0.6), FakeReplica(1, latency_s=0.6)]
    router = FleetRouter(
        replica_urls=[f.url for f in fakes],
        slo_ms=5000.0,
        health_interval_s=0.1,
        hedge=False,
        max_inflight=2,
        shed_retry_after_s=2.0,
    )
    url = f"http://127.0.0.1:{router.port}"
    outcomes = []
    lock = threading.Lock()

    def worker():
        try:
            status, _ = _post(url)
            with lock:
                outcomes.append(("ok", status, None))
        except urllib.error.HTTPError as e:
            with lock:
                outcomes.append(("shed", e.code, e.headers.get("Retry-After")))

    try:
        threads = [threading.Thread(target=worker) for _ in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        ok = [o for o in outcomes if o[0] == "ok"]
        shed = [o for o in outcomes if o[0] == "shed"]
        assert len(ok) + len(shed) == 6  # every request got an answer
        assert len(shed) >= 1, "budget of 2 never shed with 6 concurrent"
        assert all(code == 503 and ra == "2" for _, code, ra in shed)
        stats = router.stats()
        assert stats["fleet_serve/shed"] == len(shed)
    finally:
        router.close()
        for f in fakes:
            f.close()


# -- drain / undrain -----------------------------------------------------


def test_drain_under_load_drops_nothing_and_undrain_readmits(fleet):
    router, fakes = fleet
    url = f"http://127.0.0.1:{router.port}"
    failures = []
    stop = threading.Event()
    lock = threading.Lock()

    def traffic():
        while not stop.is_set():
            try:
                _post(url)
            except Exception as e:
                with lock:
                    failures.append(repr(e))
            time.sleep(0.01)

    threads = [threading.Thread(target=traffic) for _ in range(3)]
    for t in threads:
        t.start()
    try:
        time.sleep(0.3)
        req = urllib.request.Request(url + "/admin/drain?replica=0&restart=0", data=b"")
        with urllib.request.urlopen(req, timeout=10) as r:
            assert r.status == 202
            assert json.loads(r.read())["accepted"] is True
        # wait for the drain worker: in-flight waited out, replica's own
        # /admin/drain called, parked out of rotation
        deadline = time.monotonic() + 10.0
        snap = None
        while time.monotonic() < deadline:
            snap = next(
                s for s in _get(url, "/admin/replicas")["replicas"] if s["index"] == 0
            )
            if snap["drain_phase"] == "drained":
                break
            time.sleep(0.05)
        assert snap and snap["drain_phase"] == "drained", snap
        assert fakes[0].count("draining") is True
        # drained replica gets no new dispatch; traffic continues on r1
        settled = fakes[0].count("requests")
        time.sleep(0.3)
        assert fakes[0].count("requests") == settled
        # undrain re-admits once the replica reports healthy again
        fakes[0].set(draining=False)
        req = urllib.request.Request(url + "/admin/undrain?replica=0", data=b"")
        with urllib.request.urlopen(req, timeout=10):
            pass
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            if fakes[0].count("requests") > settled:
                break
            time.sleep(0.05)
        assert fakes[0].count("requests") > settled, "undrained replica got no traffic"
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10)
    assert failures == [], f"requests failed during drain: {failures[:3]}"
    assert router.stats()["fleet_serve/drains"] == 1


def test_drain_rejects_bad_replica_and_double_drain(fleet):
    router, _ = fleet
    url = f"http://127.0.0.1:{router.port}"
    with pytest.raises(urllib.error.HTTPError) as ei:
        req = urllib.request.Request(url + "/admin/drain?replica=7", data=b"")
        urllib.request.urlopen(req, timeout=10)
    assert ei.value.code == 400
    assert router.drain_replica(0, restart=False) is True
    assert router.drain_replica(0, restart=False) is False  # already draining


# -- stats / schema ------------------------------------------------------


def test_stats_aggregates_replica_burn_and_validates(fleet):
    from moco_tpu.obs import schema

    router, fakes = fleet
    url = f"http://127.0.0.1:{router.port}"
    fakes[0].set(stats_extra={"serve/burn_rate_60s": 0.5, "serve/burn_rate_600s": 0.2})
    fakes[1].set(stats_extra={"serve/burn_rate_60s": 1.5, "serve/burn_rate_600s": 0.4})
    for _ in range(4):
        _post(url)
    deadline = time.monotonic() + 5.0
    stats = {}
    while time.monotonic() < deadline:  # poller must re-read /stats
        stats = _get(url, "/stats")
        if stats.get("fleet_serve/burn_rate_60s_mean") is not None:
            break
        time.sleep(0.05)
    assert stats["fleet_serve/burn_rate_60s_min"] == 0.5
    assert stats["fleet_serve/burn_rate_60s_max"] == 1.5
    assert stats["fleet_serve/burn_rate_60s_mean"] == pytest.approx(1.0)
    assert stats["fleet_serve/replicas"] == 2
    assert stats["fleet_serve/replicas_healthy"] == 2
    assert stats["fleet_serve/requests"] == 4
    assert stats["fleet_serve/dispatch_0"] + stats["fleet_serve/dispatch_1"] >= 4
    assert 0.0 < stats["fleet_serve/slo_objective"] < 1.0
    problems = schema.validate_line({"step": 1, "time": 0.0, **stats})
    assert problems == [], problems


def test_router_needs_at_least_one_replica():
    with pytest.raises(ValueError):
        FleetRouter(replica_urls=[])
    with pytest.raises(ValueError):
        FleetRouter()


# -- batcher drain -------------------------------------------------------


def _echo_run_batch(images, want_neighbors):
    return {"embeddings": np.ones((images.shape[0], 4), np.float32)}, [
        (images.shape[0], images.shape[0])
    ]


def test_batcher_drain_flushes_accepted_riders():
    # an SLO so lax nothing would flush for 30s on its own: the flushes
    # below can only come from drain()
    b = ContinuousBatcher(_echo_run_batch, max_batch=64, slo_ms=60000.0)
    imgs = np.zeros((1, 4, 4, 3), np.uint8)
    futs = [b.submit(imgs) for _ in range(3)]
    t0 = time.perf_counter()
    assert b.drain(timeout=10.0) is True
    assert time.perf_counter() - t0 < 5.0  # not the coalescing deadline
    for f in futs:
        out = f.result(timeout=1.0)
        assert out["embeddings"].shape == (1, 4)
    with pytest.raises(BatcherClosedError):
        b.submit(imgs)
    assert b.closed


def test_batcher_drain_idempotent_and_empty():
    b = ContinuousBatcher(_echo_run_batch, max_batch=8, slo_ms=100.0)
    assert b.drain(timeout=5.0) is True
    assert b.drain(timeout=5.0) is True


# -- server drain --------------------------------------------------------


class _FakeEngine:
    buckets = (1, 4)
    recompiles_after_warmup = 0
    num_features = 4
    image_size = 4

    def warmup(self):
        pass

    def embed(self, images, stages=None):
        return np.ones((images.shape[0], 4), np.float32), [
            (images.shape[0], images.shape[0])
        ]


def test_server_admin_drain_flips_healthz_and_rejects_new_work():
    from moco_tpu.serve.server import ServeServer

    server = ServeServer(_FakeEngine(), index=None, port=0, slo_ms=500.0)
    url = f"http://127.0.0.1:{server.port}"
    imgs = np.zeros((1, 4, 4, 3), np.uint8)
    try:
        req = urllib.request.Request(
            url + "/embed", data=imgs.tobytes(),
            headers={"X-Image-Shape": "1,4,4,3"},
        )
        with urllib.request.urlopen(req, timeout=30) as r:
            assert json.loads(r.read())["request_id"].startswith("r0-")
        assert _get(url, "/healthz")["ok"] is True
        drain_req = urllib.request.Request(url + "/admin/drain?timeout=10", data=b"")
        with urllib.request.urlopen(drain_req, timeout=30) as r:
            body = json.loads(r.read())
        assert body["draining"] is True and body["drained"] is True
        h = _get(url, "/healthz")
        assert h["ok"] is False and h["draining"] is True
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=30)
        assert ei.value.code == 503
    finally:
        server.close()


# -- kill@replica fault grammar ------------------------------------------


def test_kill_replica_grammar():
    faults.install("kill@replica=1:at=3")
    assert faults.describe() == [("kill", {"replica": 1, "at": 3})]
    faults.clear()
    with pytest.raises(ValueError, match="host"):
        faults.install("kill@at=2")
    with pytest.raises(ValueError, match="mutually exclusive"):
        faults.install("kill@host=2:replica=1")


def test_kill_replica_fires_on_kth_request(monkeypatch):
    exits = []
    monkeypatch.setattr(faults.os, "_exit", lambda code: exits.append(code))
    faults.install("kill@replica=1:at=3")
    for _ in range(5):
        faults.maybe_kill_replica(0)  # a different replica: never fires
    assert exits == []
    faults.maybe_kill_replica(1)
    faults.maybe_kill_replica(1)
    assert exits == []
    faults.maybe_kill_replica(1)
    assert exits == [faults.KILL_EXIT_CODE]


def test_kill_host_path_ignores_replica_rules(tmp_path):
    faults.install("kill@replica=0")
    faults.maybe_kill_host(5, str(tmp_path), 0, 1)
    assert os.listdir(tmp_path) == []  # no heartbeat stamped, no exit


def test_strip_replica_kills_preserves_other_rules():
    spec = "slow@site=x:ms=5,kill@replica=1:at=3,kill@host=2,io@site=y:at=1"
    assert faults.strip_replica_kills(spec) == "slow@site=x:ms=5,kill@host=2,io@site=y:at=1"
    assert faults.strip_replica_kills("kill@replica=0") == ""
    assert faults.strip_replica_kills("") == ""
    assert faults.strip_replica_kills(None) == ""


def test_supervisor_child_env_scrubs_kill_rules():
    sup = ReplicaSupervisor(
        1, argv_for=lambda i, p: ["true"],
        env={"PATH": os.environ.get("PATH", ""),
             "MOCO_FAULTS": "kill@replica=0:at=2,slow@site=x:ms=1"},
    )
    assert sup._child_env(0, scrub_kills=False)["MOCO_FAULTS"] == (
        "kill@replica=0:at=2,slow@site=x:ms=1"
    )
    assert sup._child_env(0, scrub_kills=True)["MOCO_FAULTS"] == "slow@site=x:ms=1"
    sup2 = ReplicaSupervisor(
        1, argv_for=lambda i, p: ["true"],
        env={"MOCO_FAULTS": "kill@replica=0"},
    )
    assert "MOCO_FAULTS" not in sup2._child_env(0, scrub_kills=True)


# -- supervisor (real subprocesses, stdlib-only fake replica) ------------


_FAKE_REPLICA_SRC = textwrap.dedent(
    """
    import json, sys
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    state = {"rows": 0}

    class H(BaseHTTPRequestHandler):
        def _json(self, code, obj):
            b = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Length", str(len(b)))
            self.end_headers()
            self.wfile.write(b)

        def do_GET(self):
            if self.path.startswith("/healthz"):
                self._json(200, {"ok": True, "warm": state["rows"] > 0})
            elif self.path.startswith("/stats"):
                self._json(200, {"serve/ingested_rows": state["rows"]})
            else:
                self.send_error(404)

        def do_POST(self):
            body = self.rfile.read(int(self.headers.get("Content-Length", 0)))
            if self.path.startswith("/ingest"):
                shape = self.headers.get("X-Rows-Shape", "0,0").split(",")
                state["rows"] += int(shape[0])
                self._json(200, {"index_rows": state["rows"]})
            else:
                self.send_error(404)

        def log_message(self, *a):
            pass

    ThreadingHTTPServer(("127.0.0.1", int(sys.argv[1])), H).serve_forever()
    """
)


@pytest.mark.slow
def test_supervisor_respawns_crashed_child_and_rewarms(tmp_path):
    script = tmp_path / "fake_replica.py"
    script.write_text(_FAKE_REPLICA_SRC)
    sup = ReplicaSupervisor(
        2,
        argv_for=lambda i, port: [sys.executable, str(script), str(port)],
        warm_rows_fn=lambda: np.ones((5, 4), np.float32),
        boot_timeout_s=30.0,
        term_timeout_s=10.0,
        monitor_interval_s=0.1,
        restart_backoff_s=0.05,
    )
    try:
        sup.start()
        for i in range(2):
            assert _get(sup.url(i), "/healthz")["ok"]
        # sudden death: SIGKILL replica 1 — the monitor must respawn it
        # on the SAME port and re-play the warm ingest
        sup._children[1].proc.kill()
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            kinds = [(e["kind"], e["replica"]) for e in sup.events()]
            if ("restart", 1) in kinds:
                break
            time.sleep(0.1)
        events = sup.events()
        crash = [e for e in events if e["kind"] == "exit" and e["replica"] == 1]
        assert crash and crash[0]["reason"] == "crash"
        warm = [e for e in events if e["kind"] == "warm" and e["replica"] == 1]
        assert warm and warm[0]["rows"] == 5
        assert ("restart", 1) in [(e["kind"], e["replica"]) for e in events]
        # reborn on the same port, warm dictionary replayed
        assert _get(sup.url(1), "/stats")["serve/ingested_rows"] == 5
        # graceful restart path (the drain worker's call)
        sup.restart_replica(0, graceful=True)
        events = sup.events()
        g_exit = [
            e for e in events
            if e["kind"] == "exit" and e["replica"] == 0 and e["reason"] == "restart"
        ]
        assert g_exit
        assert _get(sup.url(0), "/healthz")["ok"]
    finally:
        sup.close()
    for child in sup._children:
        assert child.proc.poll() is not None  # everything reaped


# -- distributed tracing (ISSUE 18) --------------------------------------


def _flight_requests(url: str) -> list:
    """Drain + snapshot the router's fleet flight ring."""
    return _get(url, "/debug/flight")["requests"]


def test_trace_stitches_failed_and_winning_attempts(fleet):
    router, fakes = fleet
    url = f"http://127.0.0.1:{router.port}"
    # first attempt fails WHEREVER it lands; the retry's sibling succeeds
    fakes[0].set(fail_next=1)
    fakes[1].set(fail_next=1)
    status, body = _post(url)
    assert status == 200
    assert ctxprop.parse(body.get("trace_id")) is not None  # well-formed id
    recs = [r for r in _flight_requests(url) if r["trace_id"] == body["trace_id"]]
    assert len(recs) == 1
    rec = recs[0]
    assert rec["status"] == 200 and rec["request_id"] == body["request_id"]
    outcomes = [(a["outcome"], a["winner"]) for a in rec["attempts"]]
    assert ("failed", False) in outcomes and ("ok", True) in outcomes
    failed = next(a for a in rec["attempts"] if a["outcome"] == "failed")
    winner = next(a for a in rec["attempts"] if a["winner"])
    # the retry is a distinct round of the SAME trace
    assert failed["retry_index"] < winner["retry_index"]
    assert failed["error"]
    # the winning attempt stitched the replica's in-band waterfall in
    assert winner["remote"]["request_id"] == body["request_id"]
    assert any(
        s["stage"] == "engine_execute" for s in winner["remote"]["stages"]
    )
    assert winner["net_send_ms"] is not None and winner["net_recv_ms"] is not None
    # critical-path attribution lands in the metrics line, schema-clean
    from moco_tpu.obs import schema

    stats = router.stats()
    assert stats["fleet_serve/critpath_retry_failed_ms"] > 0
    assert schema.validate_line({"step": 1, "time": 0.0, **stats}) == []


def test_hedge_loser_cancelled_with_wasted_ms_and_pure_p99():
    fakes = [FakeReplica(0, latency_s=1.5), FakeReplica(1)]
    router = FleetRouter(
        replica_urls=[f.url for f in fakes],
        slo_ms=1000.0,
        health_interval_s=0.1,
        hedge=True,
        hedge_min_ms=100.0,
        retry_base_delay_s=0.01,
    )
    url = f"http://127.0.0.1:{router.port}"
    try:
        status, body = _post(url)
        assert status == 200 and body["replica"] == 1
        # drain-under-load holdback: the loser lane is still in flight,
        # so the trace is HELD rather than emitted with a pending lane
        assert _flight_requests(url) == []
        deadline = time.monotonic() + 10.0
        recs = []
        while time.monotonic() < deadline:
            recs = [
                r for r in _flight_requests(url)
                if r["trace_id"] == body["trace_id"]
            ]
            if recs:
                break
            time.sleep(0.1)
        assert len(recs) == 1, "held-back trace never emitted"
        rec = recs[0]
        winner = next(a for a in rec["attempts"] if a["winner"])
        loser = next(a for a in rec["attempts"] if not a["winner"])
        assert winner["lane"] == "hedge" and winner["replica"] == 1
        assert loser["outcome"] == "cancelled"
        assert loser["wasted_ms"] >= 1000.0  # the slow lane's real cost
        # the cancelled lane shows up in the flattened waterfall too
        assert any(
            s["stage"] == "cancelled_hedge_r0" for s in rec["stages"]
        )
        stats = router.stats()
        assert stats["fleet_serve/hedge_wasted_ms"] >= 1000.0
        # p99 purity: only the CLIENT-OBSERVED latency entered the
        # histogram — the discarded 1.5s lane must not poison it
        assert stats["fleet_serve/p99_ms"] < 1200.0
    finally:
        router.close()
        for f in fakes:
            f.close()


def test_burst_hop_sum_matches_client_wall(fleet):
    from moco_tpu.obs import critpath

    router, fakes = fleet
    url = f"http://127.0.0.1:{router.port}"
    fakes[0].set(latency_s=0.05)
    fakes[1].set(latency_s=0.05)
    walls = {}
    lock = threading.Lock()

    def worker():
        for _ in range(3):
            t0 = time.perf_counter()
            status, body = _post(url)
            wall_ms = (time.perf_counter() - t0) * 1e3
            assert status == 200
            with lock:
                walls[body["trace_id"]] = wall_ms

    threads = [threading.Thread(target=worker) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    recs = {r["trace_id"]: r for r in _flight_requests(url)}
    assert set(walls) <= set(recs), "some traces never reached the flight ring"
    for trace_id, wall_ms in walls.items():
        attr = critpath.attribute(recs[trace_id])
        ssum = sum(attr["hops"].values())
        # hop sum == router total BY CONSTRUCTION...
        assert ssum == pytest.approx(attr["total_ms"], abs=0.01)
        # ...and the router total accounts for the client's wall (floor
        # widened vs the smoke's gate: these requests are ~50ms, where
        # one slow TCP setup is a visible fraction)
        assert abs(ssum - wall_ms) <= max(0.15 * wall_ms, 50.0), (
            f"{trace_id}: hops {ssum:.1f}ms vs wall {wall_ms:.1f}ms"
        )
        # every replica served through the front door echoed a waterfall
        assert any(h.startswith("replica_") for h in attr["hops"])


def test_router_workdir_emits_stream_anchor_and_flight_dump(tmp_path):
    from moco_tpu.obs.flight import read_flight_dumps

    fakes = [FakeReplica(0)]
    router = FleetRouter(
        replica_urls=[fakes[0].url],
        slo_ms=1000.0,
        health_interval_s=0.1,
        hedge=False,
        workdir=str(tmp_path),
    )
    url = f"http://127.0.0.1:{router.port}"
    try:
        for _ in range(3):
            _post(url)
        body = _get(url, "/debug/flight")
        assert body["requests_recorded"] >= 3
        assert body["dump_path"] and os.path.exists(body["dump_path"])
    finally:
        router.close()
        for f in fakes:
            f.close()
    # the on-demand dump is a readable flight artifact with router role
    dumps = read_flight_dumps(str(tmp_path))
    assert dumps and dumps[-1][1]["role"] == "router"
    # the Perfetto stream + clock anchor landed for trace_merge
    anchor = json.load(open(tmp_path / "heartbeat.r0.json"))
    assert anchor["role"] == "router" and anchor["trace_wall_t0"] > 0
    spans = [
        json.loads(line)
        for line in open(tmp_path / "trace_events.r0.jsonl")
        if line.strip()
    ]
    names = {s["name"] for s in spans}
    assert {"request", "router/attempt", "router/respond"} <= names
    # every attempt span carries the propagated ids the stitcher joins on
    for s in spans:
        if s["name"] == "router/attempt":
            assert ctxprop.parse(s["args"]["trace_id"]) is not None
            assert len(s["args"]["span_id"]) == ctxprop.SPAN_ID_HEX_LEN


def test_trace_disabled_router_serves_untraced():
    fakes = [FakeReplica(0)]
    router = FleetRouter(
        replica_urls=[fakes[0].url],
        slo_ms=1000.0,
        health_interval_s=0.1,
        hedge=False,
        reqtrace=False,
    )
    url = f"http://127.0.0.1:{router.port}"
    try:
        status, body = _post(url)
        assert status == 200 and "trace_id" not in body
        assert _flight_requests(url) == []
    finally:
        router.close()
        for f in fakes:
            f.close()


# -- trace_merge: the router joins the fleet timeline ---------------------


def _write_jsonl(path, records):
    with open(path, "w") as f:
        for r in records:
            f.write(json.dumps(r) + "\n")


def test_trace_merge_router_track_flow_events_and_offline_stitch(tmp_path):
    tm = load_script("trace_merge.py")
    wd = str(tmp_path)
    trace_id = "ab" * 16
    attempt_span = "cd" * 8
    # router 0: anchor wall 1000.0; one request with one attempt
    _write_jsonl(os.path.join(wd, "trace_events.r0.jsonl"), [
        {"name": "request", "ts": 0.0, "dur": 50_000.0, "tid": 1,
         "thread": "requests-0", "p": 0,
         "args": {"trace_id": trace_id, "span_id": "11" * 8,
                  "path": "/embed", "status": 200,
                  "request_id": "r1-000007"}},
        {"name": "router/ingress", "ts": 0.0, "dur": 1_000.0, "tid": 1,
         "thread": "requests-0", "p": 0, "args": {"trace_id": trace_id}},
        {"name": "router/attempt", "ts": 2_000.0, "dur": 40_000.0, "tid": 1,
         "thread": "requests-0", "p": 0,
         "args": {"trace_id": trace_id, "span_id": attempt_span,
                  "replica": 1, "retry_index": 0, "lane": "primary",
                  "breaker": "closed", "outcome": "ok", "winner": True,
                  "wasted_ms": 0.0, "error": None}},
        {"name": "router/respond", "ts": 48_000.0, "dur": 2_000.0, "tid": 1,
         "thread": "requests-0", "p": 0, "args": {"trace_id": trace_id}},
    ])
    with open(os.path.join(wd, "heartbeat.r0.json"), "w") as f:
        json.dump({"process": 0, "role": "router", "host": "routerhost",
                   "time": 1000.0, "trace_wall_t0": 1000.0}, f)
    # replica 1 in a fleet-style subdir: clock starts 0.01s later; its
    # request span parents under the router's attempt span
    sub = tmp_path / "replica1"
    sub.mkdir()
    _write_jsonl(str(sub / "trace_events.s1.jsonl"), [
        {"name": "request", "ts": 0.0, "dur": 30_000.0, "tid": 1,
         "thread": "requests-0", "p": 1,
         "args": {"request_id": "r1-000007", "rows": 1, "replica": 1,
                  "trace_id": trace_id, "span_id": "22" * 8,
                  "parent_span": attempt_span}},
        {"name": "req/engine_execute", "ts": 5_000.0, "dur": 20_000.0,
         "tid": 1, "thread": "requests-0", "p": 1,
         "args": {"request_id": "r1-000007"}},
    ])
    with open(sub / "heartbeat.s1.json", "w") as f:
        json.dump({"process": 1, "role": "serve", "host": "servehost",
                   "time": 1000.01, "trace_wall_t0": 1000.01}, f)

    out = os.path.join(wd, "merged.json")
    summary = tm.merge_traces(wd, out)
    assert summary["routers"][0]["spans"] == 4
    assert summary["serve_replicas"][1]["offset_us"] == pytest.approx(10_000.0)
    assert summary["flow_events"] == 1
    merged = json.load(open(out))
    flows = [e for e in merged["traceEvents"] if e.get("ph") in ("s", "f")]
    start = next(e for e in flows if e["ph"] == "s")
    finish = next(e for e in flows if e["ph"] == "f")
    assert start["id"] == finish["id"] == attempt_span
    assert start["pid"] == tm.ROUTER_PID_BASE
    assert finish["pid"] == tm.SERVE_PID_BASE + 1
    assert finish["bp"] == "e"
    # the arrow points forward in the aligned clock
    assert finish["ts"] > start["ts"]

    stitched = tm.stitch_traces(wd)
    assert set(stitched) == {trace_id}
    rec = stitched[trace_id]
    assert rec["total_ms"] == pytest.approx(50.0)
    assert rec["router"]["ingress_ms"] == pytest.approx(1.0)
    assert rec["router"]["respond_ms"] == pytest.approx(2.0)
    (att,) = rec["attempts"]
    assert att["winner"] and att["outcome"] == "ok"
    # clock-aligned network split: replica ingress at wall +10ms, the
    # attempt dispatched at +2ms -> 8ms send; 40 - 8 - 30 = 2ms recv
    assert att["net_send_ms"] == pytest.approx(8.0)
    assert att["net_recv_ms"] == pytest.approx(2.0)
    assert att["remote"]["request_id"] == "r1-000007"
    assert att["remote"]["stages"][0]["stage"] == "engine_execute"
    # the stitched record feeds critpath cleanly: hop sum == total
    from moco_tpu.obs import critpath

    attr = critpath.attribute(rec)
    assert sum(attr["hops"].values()) == pytest.approx(rec["total_ms"])


# -- serve_ingest --fanout -----------------------------------------------


def test_serve_ingest_fanout_discovers_and_ingests_everywhere(fleet, monkeypatch):
    router, fakes = fleet
    url = f"http://127.0.0.1:{router.port}"
    mod = load_script("serve_ingest.py")
    topo = mod.discover_replicas(url)
    assert topo == {0: fakes[0].url, 1: fakes[1].url}
    rows = np.ones((7, 4), np.float32)
    results = mod.fanout_rows(url, rows)
    assert results == {0: 7, 1: 7}
    assert fakes[0].count("ingested") == 7 and fakes[1].count("ingested") == 7
    # one replica down: its block is lost LOUDLY (None), others still land
    monkeypatch.setenv("MOCO_IO_RETRIES", "2")
    monkeypatch.setenv("MOCO_IO_RETRY_BASE", "0.01")
    fakes[1].close()
    results = mod.fanout_rows(url, rows)
    assert results[0] == 14 and results[1] is None
    fakes[1] = FakeReplica(1)  # the fixture's close() needs a live handle
