"""Fault-tolerance primitives: retrying I/O, the deterministic fault
registry, and the stall watchdog (host-only — no jax programs here).

The reference has no failure-handling story beyond "restart by hand with
--resume" (SURVEY.md §5.3); these are the unit tests for the layer that
replaces it."""

import math
import os
import time

import pytest

from moco_tpu.utils import faults, retry
from moco_tpu.utils.watchdog import STALL_EXIT_CODE, StepWatchdog


@pytest.fixture(autouse=True)
def _clean_registry():
    faults.clear()
    retry.snapshot(reset=True)
    yield
    faults.clear()
    retry.snapshot(reset=True)


# -- retry ---------------------------------------------------------------
def test_retry_succeeds_after_transient_errors():
    calls = {"n": 0}
    sleeps = []

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise IOError("transient")
        return "ok"

    assert retry.retry_call(flaky, site="t.flaky", sleep=sleeps.append) == "ok"
    assert calls["n"] == 3
    assert len(sleeps) == 2 and all(s > 0 for s in sleeps)
    assert retry.snapshot()["t.flaky"] == 2
    assert "transient" in retry.last_errors()["t.flaky"]


def test_retry_bounded_attempts_then_raises():
    def always():
        raise IOError("permanent")

    with pytest.raises(IOError):
        retry.retry_call(always, site="t.always", attempts=3, sleep=lambda s: None)
    # 3 attempts = 2 retries counted; the final failure propagates
    assert retry.snapshot()["t.always"] == 2


def test_retry_does_not_catch_logic_errors():
    def broken():
        raise ValueError("a bug, not weather")

    with pytest.raises(ValueError):
        retry.retry_call(broken, site="t.logic", sleep=lambda s: None)
    assert "t.logic" not in retry.snapshot()


def test_retry_backoff_is_bounded():
    sleeps = []

    def always():
        raise IOError("x")

    with pytest.raises(IOError):
        retry.retry_call(
            always, site="t.bound", attempts=6,
            base_delay=0.1, max_delay=0.4, sleep=sleeps.append,
        )
    # jitter is in [0.5, 1.5): every delay respects ceil * 1.5
    assert all(s <= 0.4 * 1.5 for s in sleeps)
    assert len(sleeps) == 5


def test_snapshot_reset():
    def once():
        raise IOError("x")

    with pytest.raises(IOError):
        retry.retry_call(once, site="t.reset", attempts=2, sleep=lambda s: None)
    assert retry.snapshot(reset=True) == {"t.reset": 1}
    assert retry.snapshot() == {}


# -- fault registry ------------------------------------------------------
def test_spec_parsing_and_describe():
    faults.install(
        "ckpt_truncate@step=7,io@site=data.read:at=2:times=3,"
        "nan@step=5,stall@step=3:seconds=0.01,preempt@step=9"
    )
    assert faults.enabled()
    kinds = [k for k, _ in faults.describe()]
    assert kinds == ["ckpt_truncate", "io", "nan", "stall", "preempt"]
    faults.clear()
    assert not faults.enabled() and faults.describe() == []


def test_unknown_fault_kind_fails_fast():
    with pytest.raises(ValueError):
        faults.install("typo_kind@step=1")
    with pytest.raises(ValueError):
        faults.install("nan@stpe=1")


def test_io_fault_fires_on_kth_read_at_site_only():
    faults.install("io@site=s:at=2:times=2")
    faults.maybe_io_error("s")  # read 1: fine
    with pytest.raises(IOError):
        faults.maybe_io_error("s")  # read 2: injected
    with pytest.raises(IOError):
        faults.maybe_io_error("s")  # read 3: injected (times=2)
    faults.maybe_io_error("s")  # read 4: fine again
    faults.maybe_io_error("elsewhere")  # other sites unaffected


def test_io_fault_degrades_to_logged_retry():
    """The composition the data pipeline relies on: an injected IOError
    under the retry wrapper is one logged retry, not a failure."""
    faults.install("io@site=d:at=1")

    def read():
        faults.maybe_io_error("d")
        return 7

    assert retry.retry_call(read, site="d", sleep=lambda s: None) == 7
    assert retry.snapshot()["d"] == 1


def test_nan_fault_window():
    faults.install("nan@step=3:times=2")
    assert faults.corrupt_loss(1.5, 2) == 1.5
    assert math.isnan(faults.corrupt_loss(1.5, 3))
    assert math.isnan(faults.corrupt_loss(1.5, 4))
    assert faults.corrupt_loss(1.5, 5) == 1.5


def test_stall_fires_once():
    faults.install("stall@step=2:seconds=0.05")
    t0 = time.monotonic()
    faults.maybe_stall(1)
    assert time.monotonic() - t0 < 0.04
    t0 = time.monotonic()
    faults.maybe_stall(2)
    assert time.monotonic() - t0 >= 0.05
    t0 = time.monotonic()
    faults.maybe_stall(2)  # once-only
    assert time.monotonic() - t0 < 0.04


def test_hooks_are_noops_when_disabled():
    faults.maybe_io_error("anywhere")
    faults.maybe_stall(1)
    faults.maybe_preempt(1)
    faults.maybe_kill_host(1, "/nonexistent", 0)
    assert faults.corrupt_loss(2.0, 1) == 2.0
    faults.on_checkpoint_saved("/nonexistent", 1)


# -- kill@host (elastic chaos harness) -----------------------------------
def test_kill_grammar_requires_host():
    faults.install("kill@host=2:at=5")
    assert faults.describe() == [("kill", {"host": 2, "at": 5})]
    faults.clear()
    with pytest.raises(ValueError, match="host"):
        faults.install("kill@at=5")
    with pytest.raises(ValueError):
        faults.install("kill@host=2:replica=1")  # unknown param


def test_kill_stamps_stale_heartbeat_once(tmp_path):
    """Single-process fake-fleet semantics: the fault stamps simulated
    host i's heartbeat file with an infinitely stale timestamp — once —
    and only from the trigger step onward."""
    import json
    import os

    faults.install("kill@host=3:at=4")
    faults.maybe_kill_host(3, str(tmp_path), 0, 1)  # before the trigger
    assert not os.path.exists(tmp_path / "heartbeat.p3.json")
    faults.maybe_kill_host(4, str(tmp_path), 0, 1)
    rec = json.load(open(tmp_path / "heartbeat.p3.json"))
    assert rec["process"] == 3 and rec["time"] == 0.0
    # fire-once: a later beat by a revived simulation is not re-stamped
    (tmp_path / "heartbeat.p3.json").write_text(json.dumps({"process": 3, "time": 1e12}))
    faults.maybe_kill_host(5, str(tmp_path), 0, 1)
    assert json.load(open(tmp_path / "heartbeat.p3.json"))["time"] == 1e12


# -- watchdog ------------------------------------------------------------
def test_watchdog_fires_dumps_and_exits(tmp_path):
    events = {}
    dump = tmp_path / "stacks.txt"
    wd = StepWatchdog(
        timeout=0.2,
        on_stall=lambda: events.setdefault("stall", True),
        dump_path=str(dump),
        startup_grace=0.2,  # tests beat immediately; no compile to cover
        poll=0.05,
        exit_fn=lambda code: events.setdefault("exit", code),
    )
    wd.start()
    wd.beat()
    time.sleep(0.6)  # no beats: must fire
    wd.stop()
    assert events.get("stall") is True
    assert events.get("exit") == STALL_EXIT_CODE
    assert "Thread" in dump.read_text()  # all-thread stack dump landed


def test_watchdog_beats_prevent_firing():
    fired = []
    wd = StepWatchdog(
        timeout=0.3, startup_grace=0.3, poll=0.05, exit_fn=fired.append
    )
    wd.start()
    for _ in range(10):
        time.sleep(0.05)
        wd.beat()
    wd.stop()
    assert fired == []


def test_watchdog_startup_grace_covers_compilation():
    """Before the first beat the effective timeout is the startup grace
    (first-step XLA compile can take minutes); after a beat, `timeout`
    applies."""
    fired = []
    wd = StepWatchdog(
        timeout=0.1, startup_grace=10.0, poll=0.02, exit_fn=fired.append
    )
    wd.start()
    time.sleep(0.4)  # way past timeout, inside grace, zero beats
    assert fired == []
    wd.beat()
    time.sleep(0.4)  # past timeout with beats seen: fires
    wd.stop()
    assert fired == [STALL_EXIT_CODE]


def test_watchdog_on_stall_exception_does_not_block_exit():
    events = []

    def bad_stall():
        events.append("stall")
        raise RuntimeError("emergency save failed")

    wd = StepWatchdog(
        timeout=0.1, startup_grace=0.1, poll=0.02,
        on_stall=bad_stall, exit_fn=lambda c: events.append(c),
    )
    wd.start()
    time.sleep(0.4)
    wd.stop()
    assert events == ["stall", STALL_EXIT_CODE]
