"""Fused streaming InfoNCE kernel vs the dense jnp oracle."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from moco_tpu.ops.fused_infonce import _reference, fused_infonce_loss, infonce_stats
from moco_tpu.ops.losses import cross_entropy, infonce_logits, l2_normalize, topk_accuracy

B, C, K = 16, 32, 256


@pytest.fixture(scope="module")
def data():
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = l2_normalize(jax.random.normal(ks[0], (B, C)))
    k = l2_normalize(jax.random.normal(ks[1], (B, C)))
    queue = l2_normalize(jax.random.normal(ks[2], (K, C)))
    return q, k, queue


def test_stats_match_reference(data):
    q, k, queue = data
    pos, lse, above = infonce_stats(q, k, queue, 0.2, block_k=64, interpret=True)
    rpos, rlse, rabove = _reference(q, k, queue, 0.2)
    np.testing.assert_allclose(np.asarray(pos), np.asarray(rpos), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(lse), np.asarray(rlse), rtol=1e-5, atol=1e-6)
    np.testing.assert_array_equal(np.asarray(above), np.asarray(rabove))


def test_loss_and_metrics_match_dense_chain(data):
    """Matches the existing infonce_logits → CE → topk path exactly."""
    q, k, queue = data
    loss, metrics = fused_infonce_loss(q, k, queue, 0.2, block_k=64, interpret=True)
    logits, labels = infonce_logits(q, k, queue, 0.2)
    ref_loss = cross_entropy(logits, labels)
    ref_metrics = topk_accuracy(logits, labels)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    np.testing.assert_allclose(float(metrics["acc1"]), float(ref_metrics["acc1"]), atol=1e-4)
    np.testing.assert_allclose(float(metrics["acc5"]), float(ref_metrics["acc5"]), atol=1e-4)


def test_gradient_matches_dense_chain(data):
    q, k, queue = data

    def fused(q):
        loss, _ = fused_infonce_loss(q, k, queue, 0.2, block_k=64, interpret=True)
        return loss

    def dense(q):
        logits, labels = infonce_logits(q, k, queue, 0.2)
        return cross_entropy(logits, labels)

    g_fused = jax.grad(fused)(q)
    g_dense = jax.grad(dense)(q)
    np.testing.assert_allclose(np.asarray(g_fused), np.asarray(g_dense), rtol=1e-4, atol=1e-6)


def test_gradient_chains_through_normalization(data):
    """The real call site normalizes q first — grads must chain."""
    _, k, queue = data
    raw = jax.random.normal(jax.random.PRNGKey(5), (B, C)) * 3.0

    def fused(raw):
        loss, _ = fused_infonce_loss(l2_normalize(raw), k, queue, 0.2, block_k=64, interpret=True)
        return loss

    def dense(raw):
        logits, labels = infonce_logits(l2_normalize(raw), k, queue, 0.2)
        return cross_entropy(logits, labels)

    np.testing.assert_allclose(
        np.asarray(jax.grad(fused)(raw)), np.asarray(jax.grad(dense)(raw)), rtol=1e-4, atol=1e-6
    )


def test_fallback_on_indivisible_k(data):
    q, k, queue = data
    pos, lse, above = infonce_stats(q, k, queue[:100], 0.2, block_k=64, interpret=True)
    rpos, rlse, rabove = _reference(q, k, queue[:100], 0.2)
    np.testing.assert_allclose(np.asarray(lse), np.asarray(rlse), rtol=1e-5)
    np.testing.assert_array_equal(np.asarray(above), np.asarray(rabove))
