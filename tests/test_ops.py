"""Numpy-oracle tests for loss/metric primitives (SURVEY.md §4's
recommended unit strategy — the reference itself has no tests)."""

import jax
import jax.numpy as jnp
import numpy as np

from moco_tpu.core.ema import ema_update
from moco_tpu.core.queue import check_queue_divisibility, enqueue, init_queue
from moco_tpu.ops import cross_entropy, infonce_logits, l2_normalize, topk_accuracy
import pytest


def test_l2_normalize_matches_torch_semantics():
    x = np.random.RandomState(0).randn(4, 8).astype(np.float32)
    got = np.asarray(l2_normalize(jnp.asarray(x)))
    want = x / np.maximum(np.linalg.norm(x, axis=1, keepdims=True), 1e-12)
    np.testing.assert_allclose(got, want, rtol=1e-6)
    # zero row does not produce NaN (torch normalize semantics)
    z = np.asarray(l2_normalize(jnp.zeros((1, 8))))
    assert np.all(np.isfinite(z))


def test_infonce_logits_oracle():
    rs = np.random.RandomState(1)
    q = rs.randn(6, 16).astype(np.float32)
    k = rs.randn(6, 16).astype(np.float32)
    queue = rs.randn(32, 16).astype(np.float32)
    T = 0.07
    logits, labels = infonce_logits(jnp.asarray(q), jnp.asarray(k), jnp.asarray(queue), T)
    want_pos = np.sum(q * k, axis=1, keepdims=True)
    want_neg = q @ queue.T
    np.testing.assert_allclose(np.asarray(logits), np.concatenate([want_pos, want_neg], 1) / T, rtol=2e-5)
    assert np.all(np.asarray(labels) == 0)


def test_infonce_no_grad_through_keys_or_queue():
    q = jnp.ones((2, 4))
    k = jnp.ones((2, 4))
    queue = jnp.ones((8, 4))

    def loss_wrt_k(k):
        logits, labels = infonce_logits(q, k, queue, 0.1)
        return cross_entropy(logits, labels)

    assert np.allclose(jax.grad(loss_wrt_k)(k), 0.0)


def test_cross_entropy_oracle():
    rs = np.random.RandomState(2)
    logits = rs.randn(5, 7).astype(np.float32) * 3
    labels = rs.randint(0, 7, 5)
    p = np.exp(logits - logits.max(1, keepdims=True))
    p /= p.sum(1, keepdims=True)
    want = -np.mean(np.log(p[np.arange(5), labels]))
    got = cross_entropy(jnp.asarray(logits), jnp.asarray(labels))
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_topk_accuracy():
    logits = jnp.asarray([[3.0, 2.0, 1.0, 0.0], [0.0, 1.0, 2.0, 3.0]])
    labels = jnp.asarray([0, 0])
    acc = topk_accuracy(logits, labels, ks=(1, 3))
    assert acc["acc1"] == 50.0
    assert acc["acc3"] == 50.0  # second row: label 0 ranks 4th


def test_ema_matches_numpy():
    k = {"w": jnp.asarray([1.0, 2.0]), "b": jnp.asarray(4.0)}
    q = {"w": jnp.asarray([3.0, 0.0]), "b": jnp.asarray(0.0)}
    out = ema_update(k, q, 0.9)
    np.testing.assert_allclose(out["w"], [1.0 * 0.9 + 0.3, 2.0 * 0.9])
    np.testing.assert_allclose(out["b"], 3.6)


def test_queue_fifo_and_wraparound():
    queue = init_queue(jax.random.key(0), 8, 4)
    np.testing.assert_allclose(np.linalg.norm(np.asarray(queue), axis=1), 1.0, rtol=1e-5)
    ptr = jnp.zeros((), jnp.int32)
    blocks = [jnp.full((4, 4), float(i)) for i in range(3)]
    for b in blocks:
        queue, ptr = enqueue(queue, ptr, b)
    # after 3 writes of 4 into K=8: ptr wrapped to 4; rows 0-3 = block2, 4-7 = block1
    assert int(ptr) == 4
    np.testing.assert_allclose(np.asarray(queue)[:4], 2.0)
    np.testing.assert_allclose(np.asarray(queue)[4:], 1.0)


def test_queue_divisibility_guard():
    check_queue_divisibility(4096, 256)
    with pytest.raises(ValueError):
        check_queue_divisibility(65536, 100)
