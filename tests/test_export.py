"""Export: flax ResNet → torchvision-named state_dict → detectron2 pickle.

torchvision isn't in the image (torch CPU is), so parity is checked two
ways: (1) the converted key set equals the exact torchvision resnet18
key inventory; (2) a `torch.nn.functional` forward built *from the
converted dict alone* (torch's conv/BN semantics, NCHW) numerically
matches the flax backbone's forward — which is what detectron2/timm
loading the dict would compute.
"""

import pickle

import jax
import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")
import torch.nn.functional as F

from moco_tpu.export import (
    STAGE_SIZES,
    resnet_to_torchvision,
    save_detectron2_pickle,
    torchvision_to_detectron2,
)
from moco_tpu.models import create_resnet


def _tv_resnet18_keys():
    """The exact torchvision resnet18 parameter/buffer names (minus fc and
    num_batches_tracked)."""
    keys = ["conv1.weight"]
    keys += [f"bn1.{s}" for s in ("weight", "bias", "running_mean", "running_var")]
    for stage, blocks in enumerate((2, 2, 2, 2)):
        for j in range(blocks):
            p = f"layer{stage + 1}.{j}"
            for c in (1, 2):
                keys.append(f"{p}.conv{c}.weight")
                keys += [f"{p}.bn{c}.{s}" for s in ("weight", "bias", "running_mean", "running_var")]
            if stage > 0 and j == 0:
                keys.append(f"{p}.downsample.0.weight")
                keys += [
                    f"{p}.downsample.1.{s}"
                    for s in ("weight", "bias", "running_mean", "running_var")
                ]
    return set(keys)


@pytest.fixture(scope="module")
def r18():
    """Flax resnet18 with BN stats warmed by a train-mode pass."""
    model = create_resnet("resnet18")
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 64, 64, 3))
    variables = model.init(jax.random.PRNGKey(1), x, train=False)
    params, stats = variables["params"], variables["batch_stats"]
    _, mut = model.apply(
        {"params": params, "batch_stats": stats}, x, train=True, mutable=["batch_stats"]
    )
    return model, params, mut["batch_stats"]


def test_key_inventory_matches_torchvision(r18):
    _, params, stats = r18
    sd = resnet_to_torchvision(params, stats, stage_sizes=STAGE_SIZES["resnet18"])
    assert set(sd) == _tv_resnet18_keys()


def _torch_forward(sd, x, stage_sizes):
    """Forward pass of a torchvision-style ResNet-18/34 written directly
    against the converted state dict with torch.nn.functional ops."""

    def bn(x, p):
        return F.batch_norm(
            x,
            torch.from_numpy(sd[f"{p}.running_mean"]),
            torch.from_numpy(sd[f"{p}.running_var"]),
            torch.from_numpy(sd[f"{p}.weight"]),
            torch.from_numpy(sd[f"{p}.bias"]),
            training=False,
            eps=1e-5,
        )

    def conv(x, p, stride=1, padding=0):
        return F.conv2d(x, torch.from_numpy(sd[f"{p}.weight"]), stride=stride, padding=padding)

    x = conv(x, "conv1", stride=2, padding=3)
    x = F.relu(bn(x, "bn1"))
    x = F.max_pool2d(x, 3, stride=2, padding=1)
    for stage, blocks in enumerate(stage_sizes):
        for j in range(blocks):
            p = f"layer{stage + 1}.{j}"
            stride = 2 if stage > 0 and j == 0 else 1
            residual = x
            y = F.relu(bn(conv(x, f"{p}.conv1", stride=stride, padding=1), f"{p}.bn1"))
            y = bn(conv(y, f"{p}.conv2", padding=1), f"{p}.bn2")
            if f"{p}.downsample.0.weight" in sd:
                residual = bn(conv(x, f"{p}.downsample.0", stride=stride), f"{p}.downsample.1")
            x = F.relu(y + residual)
    return x.mean(dim=(2, 3))


def test_functional_forward_parity(r18):
    model, params, stats = r18
    sd = resnet_to_torchvision(params, stats, stage_sizes=STAGE_SIZES["resnet18"])
    x = np.random.default_rng(0).normal(size=(2, 64, 64, 3)).astype(np.float32) * 0.5
    flax_out = model.apply({"params": params, "batch_stats": stats}, jnp.asarray(x), train=False)
    with torch.no_grad():
        torch_out = _torch_forward(sd, torch.from_numpy(x.transpose(0, 3, 1, 2)), (2, 2, 2, 2))
    np.testing.assert_allclose(np.asarray(flax_out), torch_out.numpy(), rtol=2e-3, atol=2e-3)


def test_detectron2_renaming():
    sd = {
        "conv1.weight": np.zeros(1),
        "bn1.running_mean": np.zeros(1),
        "layer1.0.conv2.weight": np.zeros(1),
        "layer4.1.downsample.0.weight": np.zeros(1),
        "layer4.1.downsample.1.running_var": np.zeros(1),
    }
    d2 = torchvision_to_detectron2(sd)
    assert "stem.conv1.weight" in d2
    assert "stem.conv1.norm.running_mean" in d2
    assert "res2.0.conv2.weight" in d2
    assert "res5.1.shortcut.weight" in d2
    assert "res5.1.shortcut.norm.running_var" in d2


def test_detectron2_pickle_envelope(tmp_path, r18):
    _, params, stats = r18
    sd = resnet_to_torchvision(params, stats, stage_sizes=STAGE_SIZES["resnet18"])
    path = str(tmp_path / "out.pkl")
    save_detectron2_pickle(sd, path)
    with open(path, "rb") as f:
        blob = pickle.load(f)
    assert blob["__author__"] == "MOCO"
    assert blob["matching_heuristics"] is True
    assert any(k.startswith("stem.") for k in blob["model"])
