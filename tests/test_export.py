"""Export: flax ResNet → torchvision-named state_dict → detectron2 pickle.

torchvision isn't in the image (torch CPU is), so parity is checked two
ways: (1) the converted key set equals the exact torchvision resnet18
key inventory; (2) a `torch.nn.functional` forward built *from the
converted dict alone* (torch's conv/BN semantics, NCHW) numerically
matches the flax backbone's forward — which is what detectron2/timm
loading the dict would compute.
"""

import pickle

import jax
import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")
import torch.nn.functional as F

from moco_tpu.export import (
    STAGE_SIZES,
    resnet_to_torchvision,
    save_detectron2_pickle,
    torchvision_to_detectron2,
)
from moco_tpu.models import create_resnet


def _tv_resnet18_keys():
    """The exact torchvision resnet18 parameter/buffer names (minus fc and
    num_batches_tracked)."""
    keys = ["conv1.weight"]
    keys += [f"bn1.{s}" for s in ("weight", "bias", "running_mean", "running_var")]
    for stage, blocks in enumerate((2, 2, 2, 2)):
        for j in range(blocks):
            p = f"layer{stage + 1}.{j}"
            for c in (1, 2):
                keys.append(f"{p}.conv{c}.weight")
                keys += [f"{p}.bn{c}.{s}" for s in ("weight", "bias", "running_mean", "running_var")]
            if stage > 0 and j == 0:
                keys.append(f"{p}.downsample.0.weight")
                keys += [
                    f"{p}.downsample.1.{s}"
                    for s in ("weight", "bias", "running_mean", "running_var")
                ]
    return set(keys)


@pytest.fixture(scope="module")
def r18():
    """Flax resnet18 with BN stats warmed by a train-mode pass."""
    model = create_resnet("resnet18")
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 64, 64, 3))
    variables = model.init(jax.random.PRNGKey(1), x, train=False)
    params, stats = variables["params"], variables["batch_stats"]
    _, mut = model.apply(
        {"params": params, "batch_stats": stats}, x, train=True, mutable=["batch_stats"]
    )
    return model, params, mut["batch_stats"]


def test_key_inventory_matches_torchvision(r18):
    _, params, stats = r18
    sd = resnet_to_torchvision(params, stats, stage_sizes=STAGE_SIZES["resnet18"])
    assert set(sd) == _tv_resnet18_keys()


def _torch_forward(sd, x, stage_sizes):
    """Forward pass of a torchvision-style ResNet-18/34 written directly
    against the converted state dict with torch.nn.functional ops."""

    def bn(x, p):
        return F.batch_norm(
            x,
            torch.from_numpy(sd[f"{p}.running_mean"]),
            torch.from_numpy(sd[f"{p}.running_var"]),
            torch.from_numpy(sd[f"{p}.weight"]),
            torch.from_numpy(sd[f"{p}.bias"]),
            training=False,
            eps=1e-5,
        )

    def conv(x, p, stride=1, padding=0):
        return F.conv2d(x, torch.from_numpy(sd[f"{p}.weight"]), stride=stride, padding=padding)

    x = conv(x, "conv1", stride=2, padding=3)
    x = F.relu(bn(x, "bn1"))
    x = F.max_pool2d(x, 3, stride=2, padding=1)
    for stage, blocks in enumerate(stage_sizes):
        for j in range(blocks):
            p = f"layer{stage + 1}.{j}"
            stride = 2 if stage > 0 and j == 0 else 1
            residual = x
            y = F.relu(bn(conv(x, f"{p}.conv1", stride=stride, padding=1), f"{p}.bn1"))
            y = bn(conv(y, f"{p}.conv2", padding=1), f"{p}.bn2")
            if f"{p}.downsample.0.weight" in sd:
                residual = bn(conv(x, f"{p}.downsample.0", stride=stride), f"{p}.downsample.1")
            x = F.relu(y + residual)
    return x.mean(dim=(2, 3))


def test_functional_forward_parity(r18):
    model, params, stats = r18
    sd = resnet_to_torchvision(params, stats, stage_sizes=STAGE_SIZES["resnet18"])
    x = np.random.default_rng(0).normal(size=(2, 64, 64, 3)).astype(np.float32) * 0.5
    flax_out = model.apply({"params": params, "batch_stats": stats}, jnp.asarray(x), train=False)
    with torch.no_grad():
        torch_out = _torch_forward(sd, torch.from_numpy(x.transpose(0, 3, 1, 2)), (2, 2, 2, 2))
    np.testing.assert_allclose(np.asarray(flax_out), torch_out.numpy(), rtol=2e-3, atol=2e-3)


def test_detectron2_renaming():
    sd = {
        "conv1.weight": np.zeros(1),
        "bn1.running_mean": np.zeros(1),
        "layer1.0.conv2.weight": np.zeros(1),
        "layer4.1.downsample.0.weight": np.zeros(1),
        "layer4.1.downsample.1.running_var": np.zeros(1),
    }
    d2 = torchvision_to_detectron2(sd)
    assert "stem.conv1.weight" in d2
    assert "stem.conv1.norm.running_mean" in d2
    assert "res2.0.conv2.weight" in d2
    assert "res5.1.shortcut.weight" in d2
    assert "res5.1.shortcut.norm.running_var" in d2


def test_detectron2_pickle_envelope(tmp_path, r18):
    _, params, stats = r18
    sd = resnet_to_torchvision(params, stats, stage_sizes=STAGE_SIZES["resnet18"])
    path = str(tmp_path / "out.pkl")
    save_detectron2_pickle(sd, path)
    with open(path, "rb") as f:
        blob = pickle.load(f)
    assert blob["__author__"] == "MOCO"
    assert blob["matching_heuristics"] is True
    assert any(k.startswith("stem.") for k in blob["model"])


# ---- ViT -> timm export ---------------------------------------------------


@pytest.fixture(scope="module")
def vit_tiny():
    from moco_tpu.models.vit import create_vit

    m = create_vit("vit_tiny", image_size=32, patch_size=4)
    v = m.init(jax.random.PRNGKey(3), jnp.zeros((1, 32, 32, 3)), train=False)
    return m, v["params"]


def test_vit_timm_key_inventory(vit_tiny):
    from moco_tpu.export import vit_to_timm

    _, params = vit_tiny
    sd = vit_to_timm(params, patch_size=4, image_size=32)
    for k in (
        "patch_embed.proj.weight", "patch_embed.proj.bias", "cls_token",
        "pos_embed", "norm.weight", "norm.bias",
        "blocks.0.attn.qkv.weight", "blocks.0.attn.proj.weight",
        "blocks.3.mlp.fc2.bias",
    ):
        assert k in sd, k
    d = sd["patch_embed.proj.weight"].shape[0]
    assert sd["blocks.0.attn.qkv.weight"].shape == (3 * d, d)
    assert sd["pos_embed"].shape == (1, 1 + (32 // 4) ** 2, d)


def test_vit_timm_forward_parity(vit_tiny):
    """A timm-style torch forward from the exported dict must reproduce
    the flax backbone's cls features — the transfer guarantee."""
    import torch

    from moco_tpu.export import vit_to_timm

    m, params = vit_tiny
    sd_np = vit_to_timm(params, patch_size=4, image_size=32)
    sd = {k: torch.from_numpy(np.ascontiguousarray(v)) for k, v in sd_np.items()}

    x = np.asarray(
        jax.random.normal(jax.random.PRNGKey(9), (2, 32, 32, 3)), np.float32
    )
    want = np.asarray(m.apply({"params": params}, jnp.asarray(x), train=False))

    heads, depth = 3, 4
    t = torch.from_numpy(x).permute(0, 3, 1, 2)  # NCHW
    t = F.conv2d(
        t, sd["patch_embed.proj.weight"].float(),
        bias=sd["patch_embed.proj.bias"].float(), stride=4,
    )
    b, d, gh, gw = t.shape
    t = t.flatten(2).transpose(1, 2)  # (B, N, D) row-major tokens
    cls = sd["cls_token"].expand(b, -1, -1)
    t = torch.cat([cls, t], dim=1) + sd["pos_embed"].float()
    hd = d // heads
    for i in range(depth):
        pre = f"blocks.{i}"
        y = F.layer_norm(t, (d,), sd[f"{pre}.norm1.weight"], sd[f"{pre}.norm1.bias"], eps=1e-6)
        qkv = F.linear(y, sd[f"{pre}.attn.qkv.weight"], sd[f"{pre}.attn.qkv.bias"])
        q, k, v = qkv.chunk(3, dim=-1)

        def split(z):
            return z.view(b, -1, heads, hd).transpose(1, 2)  # (B, H, N, hd)

        q, k, v = split(q), split(k), split(v)
        attn = (q @ k.transpose(-2, -1)) / hd**0.5
        y = (attn.softmax(dim=-1) @ v).transpose(1, 2).reshape(b, -1, d)
        y = F.linear(y, sd[f"{pre}.attn.proj.weight"], sd[f"{pre}.attn.proj.bias"])
        t = t + y
        y = F.layer_norm(t, (d,), sd[f"{pre}.norm2.weight"], sd[f"{pre}.norm2.bias"], eps=1e-6)
        y = F.linear(y, sd[f"{pre}.mlp.fc1.weight"], sd[f"{pre}.mlp.fc1.bias"])
        # flax nn.gelu defaults to the tanh approximation
        y = F.gelu(y, approximate="tanh")
        y = F.linear(y, sd[f"{pre}.mlp.fc2.weight"], sd[f"{pre}.mlp.fc2.bias"])
        t = t + y
    t = F.layer_norm(t, (d,), sd["norm.weight"], sd["norm.bias"], eps=1e-6)
    got = t[:, 0].numpy()
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)
