"""Telemetry layer (moco_tpu/obs): tracer, sinks, probe, health
reductions, schema — plus the satellite regressions (batched device_get
on the logging path, multi-host print silencing, profiler reentrancy)."""

import json
import os
import threading
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from moco_tpu import obs
from moco_tpu.obs import health, schema, sinks
from moco_tpu.obs.stepstats import StepTimeProbe, memory_payload
from moco_tpu.obs.trace import Tracer


# -- span tracer ---------------------------------------------------------


def test_tracer_nesting_and_chrome_export(tmp_path):
    t = Tracer()
    with t.span("epoch", epoch=0):
        with t.span("data_wait"):
            pass
        with t.span("step", step=1):
            pass
    spans = t.snapshot()
    by_name = {s["name"]: s for s in spans}
    # children close before the parent -> parent is last; depth recorded
    assert [s["name"] for s in spans] == ["data_wait", "step", "epoch"]
    assert by_name["epoch"]["depth"] == 0
    assert by_name["data_wait"]["depth"] == 1
    # timestamp containment (what Perfetto renders nesting from)
    e = by_name["epoch"]
    for child in ("data_wait", "step"):
        c = by_name[child]
        assert e["ts"] <= c["ts"]
        assert c["ts"] + c["dur"] <= e["ts"] + e["dur"] + 1e-3
    assert by_name["step"]["args"] == {"step": 1}

    path = t.export_chrome(str(tmp_path / "trace.json"))
    trace = json.load(open(path))
    names = {ev["name"] for ev in trace["traceEvents"] if ev.get("ph") == "X"}
    assert {"epoch", "data_wait", "step"} <= names
    # thread-name metadata events for Perfetto track labels
    assert any(ev.get("ph") == "M" for ev in trace["traceEvents"])


def test_tracer_span_survives_exception():
    t = Tracer()
    with pytest.raises(RuntimeError):
        with t.span("boom"):
            raise RuntimeError("x")
    (s,) = t.snapshot()
    assert s["name"] == "boom" and s["error"] == "RuntimeError"


def test_tracer_threads_get_own_tracks(tmp_path):
    t = Tracer(jsonl_path=str(tmp_path / "spans.jsonl"))

    def worker():
        with t.span("producer_work"):
            pass

    th = threading.Thread(target=worker, name="producer")
    with t.span("main_work"):
        th.start()
        th.join()
    tids = {s["tid"] for s in t.snapshot()}
    assert len(tids) == 2
    # streaming JSONL got every span, even from the worker thread
    lines = [json.loads(l) for l in open(tmp_path / "spans.jsonl")]
    assert {l["name"] for l in lines} == {"producer_work", "main_work"}
    t.close()


def test_module_level_span_noop_without_tracer():
    assert obs.get_tracer() is None
    with obs.span("free"):  # must not raise, must not record anywhere
        pass
    obs.instant("marker")  # likewise


def test_set_tracer_install_and_restore():
    t = Tracer()
    prev = obs.set_tracer(t)
    try:
        with obs.span("wired"):
            pass
    finally:
        obs.set_tracer(prev)
    assert [s["name"] for s in t.snapshot()] == ["wired"]
    assert obs.get_tracer() is prev


def test_tracer_bounds_memory_not_stream(tmp_path):
    t = Tracer(jsonl_path=str(tmp_path / "s.jsonl"), max_spans=2)
    for i in range(5):
        with t.span(f"s{i}"):
            pass
    assert len(t.snapshot()) == 2  # memory bounded
    assert t._dropped == 3
    assert len(open(tmp_path / "s.jsonl").readlines()) == 5  # stream complete
    t.close()


# -- sinks ---------------------------------------------------------------


def test_jsonl_sink_batches_device_transfers(tmp_path, monkeypatch):
    """Satellite regression: N device-array metrics must cost ONE
    transfer, not N blocking per-field float() syncs."""
    calls = {"n": 0}
    real = sinks._DEVICE_GET

    def counting(x):
        calls["n"] += 1
        return real(x)

    monkeypatch.setattr(sinks, "_DEVICE_GET", counting)
    w = sinks.JsonlSink(str(tmp_path))
    payload = {f"m{i}": jnp.float32(i) for i in range(5)}
    payload["host_val"] = 1.25  # host values must not force a transfer
    w.write(3, payload)
    w.close()
    assert calls["n"] == 1
    rec = json.loads(open(w.path).read())
    assert rec["m4"] == 4.0 and rec["host_val"] == 1.25

    calls["n"] = 0
    w2 = sinks.JsonlSink(str(tmp_path), filename="h.jsonl")
    w2.write(1, {"a": 1.0, "b": 2})  # pure-host payload: zero transfers
    w2.close()
    assert calls["n"] == 0


def test_multisink_gathers_once_for_all_sinks(tmp_path, monkeypatch):
    calls = {"n": 0}
    real = sinks._DEVICE_GET

    def counting(x):
        calls["n"] += 1
        return real(x)

    monkeypatch.setattr(sinks, "_DEVICE_GET", counting)
    ms = sinks.build_sinks("jsonl,csv", str(tmp_path))
    ms.write(1, {f"m{i}": jnp.float32(i) for i in range(4)})
    ms.close()
    assert calls["n"] == 1  # one fetch upstream of the whole fan-out


def test_jsonl_sink_scrubs_arrays_and_nonfinite(tmp_path):
    w = sinks.JsonlSink(str(tmp_path))
    w.write(
        1,
        {
            "hist": np.array([1.0, float("nan"), 3.0]),
            "jarr": jnp.arange(3),
            "bad": float("inf"),
            "none": None,
        },
    )
    w.close()
    rec = schema.loads_strict(open(w.path).read())  # strict: no NaN literals
    assert rec["hist"] == [1.0, None, 3.0]
    assert rec["jarr"] == [0, 1, 2]
    assert rec["bad"] is None and rec["none"] is None


def test_csv_sink_grows_header(tmp_path):
    import csv as csvmod

    s = sinks.CsvSink(str(tmp_path))
    s.write(1, {"loss": 1.0})
    s.write(2, {"loss": 0.9, "ema_drift": 0.01, "queue_age_hist": [1, 0]})
    rows = list(csvmod.DictReader(open(s.path)))
    assert len(rows) == 2
    assert rows[0]["ema_drift"] == ""  # backfilled on rewrite
    assert rows[1]["ema_drift"] == "0.01"
    assert json.loads(rows[1]["queue_age_hist"]) == [1, 0]
    s.close()


def test_build_sinks_always_includes_jsonl(tmp_path):
    ms = sinks.build_sinks("csv", str(tmp_path))
    assert ms.primary is not None and ms.path.endswith("metrics.jsonl")
    ms.write(1, {"loss": 1.0})
    ms.close()
    assert os.path.exists(tmp_path / "metrics.jsonl")
    assert os.path.exists(tmp_path / "metrics.csv")


def test_build_sinks_unknown_name_raises(tmp_path):
    with pytest.raises(ValueError, match="unknown metric sink"):
        sinks.build_sinks("jsonl,grafana", str(tmp_path))


def test_register_sink_plugs_into_spec(tmp_path):
    seen = []

    class Capture(sinks.Sink):
        def __init__(self, workdir):
            pass

        def write(self, step, payload):
            seen.append((step, dict(payload)))

    sinks.register_sink("capture", Capture)
    try:
        ms = sinks.build_sinks("capture", str(tmp_path))
        ms.write(7, {"loss": 0.5})
        ms.close()
    finally:
        del sinks.SINK_REGISTRY["capture"]
    assert seen and seen[0][0] == 7


def test_secondary_sink_failure_never_kills_logging(tmp_path):
    class Broken(sinks.Sink):
        def write(self, step, payload):
            raise IOError("disk full")

    primary = sinks.JsonlSink(str(tmp_path))
    ms = sinks.MultiSink([primary, Broken()], primary=primary)
    ms.write(1, {"loss": 1.0})  # must not raise
    ms.close()
    assert json.loads(open(primary.path).read())["loss"] == 1.0


def test_tensorboard_sink_unavailable_raises_clearly(tmp_path):
    have_tb = True
    try:
        import tensorboardX  # noqa: F401
    except ImportError:
        try:
            import torch.utils.tensorboard  # noqa: F401
        except ImportError:
            have_tb = False
    if have_tb:
        pytest.skip("a tensorboard writer is installed here")
    with pytest.raises(RuntimeError, match="tensorboardX"):
        sinks.TensorBoardSink(str(tmp_path))


# -- prometheus ----------------------------------------------------------


def test_prometheus_sink_serves_text_format():
    s = sinks.PrometheusSink(port=0)  # ephemeral port
    try:
        s.write(5, {"loss": 1.5, "ema_drift/backbone": 0.01, "event": "stall"})
        s.write(6, {"loss": 1.25})
        body = s.render()
        assert "moco_loss 1.25" in body
        assert "moco_ema_drift_backbone 0.01" in body
        assert 'moco_events_total{kind="stall"} 1' in body
        assert "# TYPE moco_loss gauge" in body
        url = f"http://127.0.0.1:{s.port}/metrics"
        with urllib.request.urlopen(url, timeout=5) as resp:
            served = resp.read().decode()
            assert resp.headers["Content-Type"].startswith("text/plain")
        assert served == body
        with pytest.raises(urllib.error.HTTPError):
            # deliberately-undeclared route: asserts the 404 path
            urllib.request.urlopen(
                f"http://127.0.0.1:{s.port}/other", timeout=5
            )  # mocolint: disable=JX016
    finally:
        s.close()


def test_prom_name_sanitization():
    assert sinks.prom_name("ema_drift/backbone") == "moco_ema_drift_backbone"
    assert sinks.prom_name("acc@1") == "moco_acc_1"
    assert sinks.prom_name("0weird") == "moco__0weird"


# -- multi-host console silencing ---------------------------------------


def test_progress_meter_silent_on_nonzero_process(capsys, monkeypatch):
    """Reference behavior (`main_moco.py:~L145`): non-master ranks print
    nothing; the formatted line is still returned for per-process use."""
    from moco_tpu.utils.metrics import AverageMeter, ProgressMeter, print0

    m = AverageMeter("Loss", ":.2f")
    m.update(1.0)
    p = ProgressMeter(10, [m], prefix="Epoch: [0]")

    monkeypatch.setattr(jax, "process_index", lambda: 1)
    line = p.display(3)
    print0("driver info line")
    assert capsys.readouterr().out == ""  # silent, but...
    assert "Loss" in line  # ...the line is still produced

    monkeypatch.setattr(jax, "process_index", lambda: 0)
    p.display(3)
    print0("driver info line")
    out = capsys.readouterr().out
    assert "Loss" in out and "driver info line" in out


# -- profiler reentrancy + windowed capture ------------------------------


class _FakeProfiler:
    """Stands in for jax.profiler: records start/stop calls and can be
    armed to raise on start (the dangling-trace failure mode)."""

    def __init__(self):
        self.calls = []
        self.active = False

    def start_trace(self, logdir):
        if self.active:
            self.calls.append(("start_fail", logdir))
            raise RuntimeError("profiler already active")
        self.active = True
        self.calls.append(("start", logdir))

    def stop_trace(self):
        if not self.active:
            self.calls.append(("stop_fail",))
            raise RuntimeError("no active profiler")
        self.active = False
        self.calls.append(("stop",))


@pytest.fixture
def fake_profiler(monkeypatch):
    from moco_tpu.utils import metrics as um

    fake = _FakeProfiler()
    monkeypatch.setattr(jax, "profiler", fake)
    monkeypatch.setitem(um._profiler_state, "active", False)
    return fake


def test_profiler_trace_recovers_from_dangling_trace(fake_profiler):
    from moco_tpu.utils.metrics import profiler_trace

    # someone (a crashed previous region, another library) left a trace
    # running: start will raise once
    fake_profiler.active = True
    with profiler_trace("/tmp/prof"):
        assert fake_profiler.active  # our trace is running now
    assert not fake_profiler.active  # and was stopped
    # the dangler was stopped, then start retried and succeeded
    assert ("start_fail", "/tmp/prof") in fake_profiler.calls
    assert fake_profiler.calls[-2:] == [("start", "/tmp/prof"), ("stop",)]


def test_profiler_trace_reentrant_inner_is_noop(fake_profiler):
    from moco_tpu.utils.metrics import profiler_trace

    with profiler_trace("/tmp/a"):
        with profiler_trace("/tmp/b"):  # inner: no crash, no double-start
            pass
        assert fake_profiler.active  # inner exit didn't stop the outer
    assert not fake_profiler.active
    starts = [c for c in fake_profiler.calls if c[0] == "start"]
    assert len(starts) == 1


def test_profiler_window_captures_half_open_range(fake_profiler):
    from moco_tpu.utils.metrics import ProfilerWindow

    w = ProfilerWindow("/tmp/w", 2, 4)
    for step in range(6):
        w.on_step(step)
        if step < 2 or step >= 4:
            assert not fake_profiler.active
        else:
            assert fake_profiler.active
    w.close()
    assert [c[0] for c in fake_profiler.calls] == ["start", "stop"]


def test_profiler_window_close_stops_open_capture(fake_profiler):
    from moco_tpu.utils.metrics import ProfilerWindow

    w = ProfilerWindow("/tmp/w", 0, 100)
    w.on_step(0)
    assert fake_profiler.active
    w.close()  # early exit / preemption path
    assert not fake_profiler.active
    w.close()  # idempotent


def test_parse_profile_steps():
    from moco_tpu.utils.metrics import parse_profile_steps

    assert parse_profile_steps("10:20") == (10, 20)
    for bad in ("20:10", "5", "a:b", "-1:4"):
        with pytest.raises(ValueError):
            parse_profile_steps(bad)


# -- step-time probe + memory gauges -------------------------------------


def test_step_probe_sampling_schedule_and_payload():
    p = StepTimeProbe(every=3)
    assert [p.should_sample(s) for s in range(6)] == [True, False, False, True, False, False]
    p.data_wait(0.25)
    p.dispatched(0.03)
    p.step_done(0.5)
    pay = p.payload()
    assert pay == {"t_data": 0.25, "t_step": 0.5}  # no sample yet
    p.device_block(0.4)
    pay = p.payload()
    assert pay["t_dispatch"] == 0.03 and pay["t_device"] == 0.4
    disabled = StepTimeProbe(every=0)
    assert not any(disabled.should_sample(s) for s in range(10))


def test_memory_payload_schema_locked():
    pay = memory_payload()
    assert set(pay) == {"hbm_live_bytes", "hbm_peak_bytes", "hbm_headroom_bytes"}
    for k, v in pay.items():  # number on real backends, null on CPU hosts
        if k == "hbm_headroom_bytes":
            # headroom may legitimately be negative transiently (limit
            # accounting vs allocator high-water differences)
            assert v is None or isinstance(v, int)
        else:
            assert v is None or (isinstance(v, int) and v >= 0)


def test_tree_shard_bytes_counts_shards_not_replicas():
    from jax.sharding import NamedSharding, PartitionSpec as P

    from moco_tpu.obs.stepstats import tree_shard_bytes
    from moco_tpu.parallel import create_mesh

    mesh = create_mesh(num_data=8)
    full = jnp.zeros((8, 128), jnp.float32)
    replicated = jax.device_put(full, NamedSharding(mesh, P()))
    sharded = jax.device_put(full, NamedSharding(mesh, P("data", None)))
    assert tree_shard_bytes({"a": replicated}) == 8 * 128 * 4
    assert tree_shard_bytes({"a": sharded}) == 8 * 128 * 4 // 8
    # plain numpy leaves count their full bytes
    assert tree_shard_bytes({"a": np.zeros((4,), np.float32)}) == 16


# -- health reductions (jit-compatible by construction) ------------------


def _toy_params(scale=1.0):
    return {
        "backbone": {"w": jnp.full((4, 4), scale), "b": jnp.zeros((4,))},
        "head": {"w": jnp.full((4, 2), scale)},
    }


def test_ema_drift_groups_and_global():
    out = jax.jit(health.ema_drift)(_toy_params(1.0), _toy_params(0.9))
    assert set(out) == {"ema_drift", "ema_drift/backbone", "ema_drift/head"}
    # identical trees -> zero drift
    zero = jax.jit(health.ema_drift)(_toy_params(1.0), _toy_params(1.0))
    assert float(zero["ema_drift"]) == 0.0
    # relative drift of 10% everywhere
    np.testing.assert_allclose(float(out["ema_drift"]), 0.1, rtol=1e-5)


def test_logit_stats_from_dense_matches_mask_computation():
    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.normal(size=(6, 10)).astype(np.float32))
    labels = jnp.asarray(rng.integers(0, 10, size=6).astype(np.int32))
    out = jax.jit(health.logit_stats_from_dense)(logits, labels)
    lg = np.asarray(logits)
    mask = np.ones_like(lg, bool)
    mask[np.arange(6), np.asarray(labels)] = False
    np.testing.assert_allclose(float(out["logit_neg_mean"]), lg[mask].mean(), rtol=1e-5)
    np.testing.assert_allclose(
        float(out["logit_neg_std"]), lg[mask].std(), rtol=1e-4
    )
    np.testing.assert_allclose(
        float(out["logit_pos_mean"]), lg[~mask].mean(), rtol=1e-5
    )


def test_feature_stats_detects_collapse():
    rng = np.random.default_rng(1)
    healthy = rng.normal(size=(64, 16)).astype(np.float32)
    healthy /= np.linalg.norm(healthy, axis=1, keepdims=True)
    collapsed = np.tile(healthy[:1], (64, 1))
    h = jax.jit(health.feature_stats)(jnp.asarray(healthy))
    c = jax.jit(health.feature_stats)(jnp.asarray(collapsed))
    assert float(h["feature_std"]) > 10 * float(c["feature_std"])
    assert float(c["feature_dim_active"]) == 0.0
    assert float(h["feature_dim_active"]) == 16.0


def test_queue_age_warmup_and_steady_state():
    f = jax.jit(health.queue_age, static_argnums=(1, 2))
    # steady state: K=64, B=16 -> 4 batches of ages 1..4
    out = f(jnp.int32(100), 64, 16)
    assert float(out["queue_age_mean"]) == 2.5
    assert float(out["queue_age_max"]) == 4.0
    np.testing.assert_allclose(np.asarray(out["queue_age_hist"]).sum(), 1.0, rtol=1e-6)
    # warmup: at step 2 the older slots are capped at the run's age
    out2 = f(jnp.int32(2), 64, 16)
    assert float(out2["queue_age_mean"]) == pytest.approx((1 + 2 + 2 + 2) / 4)
    # step 0: nothing enqueued yet, ages clamp to zero
    out0 = f(jnp.int32(0), 64, 16)
    assert float(out0["queue_age_mean"]) == 0.0


def test_health_summary_runs_fully_jitted():
    """The acceptance bullet's jit-compatibility proof: the whole bundle
    traces and lowers with no host round-trip (a float()/np call inside
    would throw TracerError at trace time)."""
    q = jnp.asarray(np.random.default_rng(2).normal(size=(8, 4)), jnp.float32)

    @jax.jit
    def bundle(params_q, params_k, q, pos, neg, step):
        return health.health_summary(
            params_q, params_k, q, pos, neg, step,
            num_negatives=64, global_batch=16,
        )

    out = bundle(
        _toy_params(1.0), _toy_params(0.95), q, q[:, 0], q @ q.T, jnp.int32(5)
    )
    for k, v in out.items():
        assert np.all(np.isfinite(np.asarray(v))), k
    assert {"ema_drift", "logit_pos_mean", "queue_age_mean", "feature_std"} <= set(out)


# -- schema --------------------------------------------------------------


def _good_train_line():
    return {
        "step": 5, "time": 1.0, "epoch": 0, "lr": 0.03, "loss": 1.0,
        "acc1": 50.0, "acc5": 90.0, "t_data": 0.1, "t_step": 0.5,
        "hbm_live_bytes": None, "hbm_peak_bytes": None,
        "ema_drift": 0.1, "ema_drift/backbone": 0.1,
        "logit_pos_mean": 3.0, "logit_neg_mean": -0.1,
        "queue_age_mean": 2.5, "queue_age_hist": [0.5, 0.5],
    }


def test_schema_accepts_driver_shapes():
    assert schema.validate_line(_good_train_line()) == []
    assert schema.validate_line({"step": 1, "time": 1.0, "event": "stall"}) == []
    assert schema.validate_line({"step": 1, "time": 1.0, "knn_top1": 88.0}) == []
    assert schema.validate_line(
        {"step": 1, "time": 1.0, "event": "nonfinite_loss", "nan_steps": 1}
    ) == []


def test_schema_rejects_bad_lines():
    assert schema.validate_line({"time": 1.0})  # no step
    line = _good_train_line()
    line.pop("lr")
    assert any("missing" in e for e in schema.validate_line(line))
    assert any(
        "unknown event" in e
        for e in schema.validate_line({"step": 1, "time": 1.0, "event": "gremlin"})
    )
    bad = _good_train_line()
    bad["io_retries"] = {"data.read": "three"}
    assert any("io_retries" in e for e in schema.validate_line(bad))
    bad2 = _good_train_line()
    bad2["ema_drift/backbone"] = "high"
    assert any("ema_drift/backbone" in e for e in schema.validate_line(bad2))


def test_schema_rejects_nonfinite_literals():
    with pytest.raises(ValueError, match="non-finite"):
        schema.loads_strict('{"step": 1, "time": 1.0, "loss": NaN}')
    errors = schema.validate_lines(['{"step": 1, "time": 1.0, "loss": Infinity}'])
    assert errors and "unparseable" in errors[0]


def test_schema_validates_real_writer_output(tmp_path):
    """The writer and the schema lock each other: whatever JsonlSink
    emits for driver-shaped payloads must validate."""
    w = sinks.JsonlSink(str(tmp_path))
    w.write(1, {k: v for k, v in _good_train_line().items() if k not in ("step", "time")})
    w.write(2, {"epoch": 0, "event": "nonfinite_loss", "nan_steps": 1})
    w.write(3, {"epoch": 0, "knn_top1": 42.0})
    w.close()
    assert schema.validate_file(w.path) == []


# -- obs_report ----------------------------------------------------------


def test_obs_report_renders_from_writer_output(tmp_path):
    from conftest import load_script

    w = sinks.JsonlSink(str(tmp_path))
    for s in range(1, 4):
        w.write(
            s,
            {
                "epoch": 0, "lr": 0.03, "loss": 2.0 / s, "acc1": 10.0 * s,
                "acc5": 20.0 * s, "t_data": 0.01, "t_step": 0.2,
                "hbm_live_bytes": None, "hbm_peak_bytes": None,
                "ema_drift": 0.01 * s, "logit_pos_mean": 3.0,
                "logit_neg_mean": -0.1, "queue_age_mean": 1.5,
                "io_retries": {"data.read": 2},
            },
        )
    w.write(4, {"epoch": 0, "event": "nonfinite_loss", "nan_steps": 1})
    w.close()
    t = Tracer()
    with t.span("epoch", epoch=0):
        pass
    t.export_chrome(str(tmp_path / "trace.json"))

    mod = load_script("obs_report.py")
    report = mod.render_report(w.path, str(tmp_path / "trace.json"))
    assert "Step-time breakdown" in report
    assert "ema_drift" in report and "0.01 -> 0.03" in report
    assert "io retries by site" in report
    assert "event @ step 4: nonfinite_loss" in report
    assert "`epoch`: " in report  # trace summary rendered
    # schema-clean input -> no violations section
    assert load_script("obs_report.py").main is not None


def test_obs_report_empty_file(tmp_path):
    from conftest import load_script

    path = tmp_path / "metrics.jsonl"
    path.write_text("")
    report = load_script("obs_report.py").render_report(str(path))
    assert "nothing to report" in report
