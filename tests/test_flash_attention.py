"""Flash-attention kernel vs dense reference (interpret mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from moco_tpu.ops.flash_attention import (
    _attn_reference,
    flash_attention,
    flash_attention_with_lse,
)

B, H, S, D = 2, 3, 256, 64


@pytest.fixture(scope="module")
def qkv():
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    return tuple(jax.random.normal(k, (B, H, S, D), jnp.float32) for k in ks)


def test_forward_matches_dense(qkv):
    q, k, v = qkv
    out, lse = flash_attention_with_lse(q, k, v, block_q=128, block_k=128, interpret=True)
    ref_out, ref_lse = _attn_reference(q, k, v, D**-0.5)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref_out), rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(lse), np.asarray(ref_lse), rtol=2e-5, atol=2e-5)


def test_odd_seq_runs_padded_kernel(qkv):
    """ViT's 197 tokens (prime — no block divides them): the kernel pads
    to the block size and masks padded keys; results must still be exact."""
    q, k, v = (x[:, :, :197] for x in qkv)
    out, lse = flash_attention_with_lse(q, k, v, interpret=True)
    ref_out, ref_lse = _attn_reference(q, k, v, D**-0.5)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref_out), rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(lse), np.asarray(ref_lse), rtol=2e-5, atol=2e-5)


def test_odd_seq_gradients_match_dense(qkv):
    """Padded-kernel backward: padded keys/queries must contribute zero."""
    q, k, v = (x[:, :, :197] for x in qkv)

    def flash_loss(q, k, v):
        return jnp.sum(flash_attention(q, k, v, interpret=True) ** 2)

    def dense_loss(q, k, v):
        return jnp.sum(_attn_reference(q, k, v, D**-0.5)[0] ** 2)

    g_flash = jax.grad(flash_loss, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
    for gf, gd in zip(g_flash, g_dense):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gd), rtol=1e-3, atol=1e-4)


def test_short_seq_dense_path(qkv):
    """S below one key block: the dense path serves it (value + grads)."""
    q, k, v = (x[:, :, :48] for x in qkv)
    out, lse = flash_attention_with_lse(q, k, v, interpret=True)
    ref_out, ref_lse = _attn_reference(q, k, v, D**-0.5)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref_out), rtol=2e-5, atol=2e-5)
    g = jax.grad(lambda q: jnp.sum(flash_attention(q, k, v, interpret=True) ** 2))(q)
    assert np.isfinite(np.asarray(g)).all()


def test_gradients_match_dense(qkv):
    q, k, v = qkv

    def flash_loss(q, k, v):
        return jnp.sum(flash_attention(q, k, v, block_q=128, block_k=128, interpret=True) ** 2)

    def dense_loss(q, k, v):
        return jnp.sum(_attn_reference(q, k, v, D**-0.5)[0] ** 2)

    g_flash = jax.grad(flash_loss, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
    for gf, gd in zip(g_flash, g_dense):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gd), rtol=1e-3, atol=1e-4)


def test_lse_gradient_path(qkv):
    """The lse output is differentiable too (ring attention needs it)."""
    q, k, v = qkv

    def loss(q):
        _, lse = flash_attention_with_lse(q, k, v, block_q=128, block_k=128, interpret=True)
        return jnp.sum(lse)

    g = jax.grad(loss)(q)
    assert np.isfinite(np.asarray(g)).all()
    assert np.abs(np.asarray(g)).max() > 0
