"""Compiled-Mosaic kernel tests on a REAL TPU (VERDICT r1 item 6).

The rest of the suite runs Pallas kernels in interpret mode on the CPU
mesh; Mosaic-vs-interpret divergence (block shape constraints, layout
rules) only surfaces on hardware. Run with:

    MOCO_TPU_TESTS=1 python -m pytest tests/test_tpu_kernels.py -q

Skipped automatically when no TPU backend is visible (i.e. in the
default CPU-pinned suite).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.skipif(
    jax.default_backend() != "tpu", reason="needs a real TPU backend"
)


def _rand(shape, key, dtype=jnp.float32):
    return jax.random.normal(jax.random.PRNGKey(key), shape, dtype)


class TestFusedInfoNCE:
    B, C, K = 64, 128, 8192
    BLOCK = 2048

    def test_stats_match_dense_oracle(self):
        from moco_tpu.ops.fused_infonce import _reference, infonce_stats

        q = _rand((self.B, self.C), 0)
        k = _rand((self.B, self.C), 1)
        queue = _rand((self.K, self.C), 2)
        q = q / jnp.linalg.norm(q, axis=-1, keepdims=True)
        k = k / jnp.linalg.norm(k, axis=-1, keepdims=True)
        queue = queue / jnp.linalg.norm(queue, axis=-1, keepdims=True)

        pos, lse, above = jax.jit(
            lambda q, k, qu: infonce_stats(q, k, qu, 0.2, self.BLOCK, False)
        )(q, k, queue)
        rpos, rlse, rabove = _reference(q, k, queue, 0.2)
        np.testing.assert_allclose(np.asarray(pos), np.asarray(rpos), rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(np.asarray(lse), np.asarray(rlse), rtol=1e-4, atol=1e-4)
        np.testing.assert_array_equal(np.asarray(above), np.asarray(rabove))

    def test_loss_grads_match_dense(self):
        from moco_tpu.ops.fused_infonce import fused_infonce_loss
        from moco_tpu.ops.losses import cross_entropy, infonce_logits

        q = _rand((self.B, self.C), 3)
        k = _rand((self.B, self.C), 4)
        queue = _rand((self.K, self.C), 5)
        k = k / jnp.linalg.norm(k, axis=-1, keepdims=True)
        queue = queue / jnp.linalg.norm(queue, axis=-1, keepdims=True)

        def fused(q):
            qn = q / jnp.linalg.norm(q, axis=-1, keepdims=True)
            loss, _ = fused_infonce_loss(qn, k, queue, 0.2, self.BLOCK, False)
            return loss

        def dense(q):
            qn = q / jnp.linalg.norm(q, axis=-1, keepdims=True)
            logits, labels = infonce_logits(qn, k, queue, 0.2)
            return cross_entropy(logits, labels)

        lf, gf = jax.jit(jax.value_and_grad(fused))(q)
        ld, gd = jax.jit(jax.value_and_grad(dense))(q)
        np.testing.assert_allclose(float(lf), float(ld), rtol=1e-4)
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gd), rtol=1e-3, atol=1e-5)


class TestFlashAttention:
    B, H, D = 2, 4, 64

    @pytest.mark.parametrize("seq", [256, 197], ids=["block-divisible", "padded"])
    def test_forward_matches_dense(self, seq):
        from moco_tpu.ops.flash_attention import _attn_reference, flash_attention_with_lse

        q, k, v = (_rand((self.B, self.H, seq, self.D), i) for i in range(3))
        out, lse = jax.jit(
            lambda q, k, v: flash_attention_with_lse(q, k, v, None, 128, 128, False)
        )(q, k, v)
        ref_out, ref_lse = _attn_reference(q, k, v, self.D**-0.5)
        # TPU fp32 dots run as bf16 passes by default; flash and dense
        # also sum in different orders — tolerances sized accordingly
        # (exactness is enforced by the interpret-mode CPU tests).
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref_out), rtol=2e-2, atol=5e-3)
        np.testing.assert_allclose(np.asarray(lse), np.asarray(ref_lse), rtol=2e-2, atol=5e-3)

    @pytest.mark.parametrize("seq", [256, 197], ids=["block-divisible", "padded"])
    def test_grads_match_dense(self, seq):
        from moco_tpu.ops.flash_attention import _attn_reference, flash_attention

        q, k, v = (_rand((self.B, self.H, seq, self.D), 10 + i) for i in range(3))

        def flash_loss(q, k, v):
            return jnp.sum(flash_attention(q, k, v, None, 128, 128, False) ** 2)

        def dense_loss(q, k, v):
            return jnp.sum(_attn_reference(q, k, v, self.D**-0.5)[0] ** 2)

        g_flash = jax.jit(jax.grad(flash_loss, argnums=(0, 1, 2)))(q, k, v)
        g_dense = jax.jit(jax.grad(dense_loss, argnums=(0, 1, 2)))(q, k, v)
        for gf, gd in zip(g_flash, g_dense):
            np.testing.assert_allclose(np.asarray(gf), np.asarray(gd), rtol=2e-2, atol=2e-2)

    def test_vit_forward_with_flash(self):
        """The wired consumer: a ViT forward on TPU using the kernel."""
        from moco_tpu.models import create_vit

        # patch 4 on 64px -> 257 tokens: above one block, exercises the
        # padded kernel (not the short-seq dense fallback)
        vit = create_vit("vit_tiny", image_size=64, patch_size=4, use_flash_attention=True)
        vit_dense = create_vit("vit_tiny", image_size=64, patch_size=4)
        x = _rand((2, 64, 64, 3), 20)
        params = jax.jit(vit.init)(jax.random.PRNGKey(0), x)
        out_flash = jax.jit(vit.apply)(params, x)
        out_dense = jax.jit(vit_dense.apply)(params, x)
        np.testing.assert_allclose(
            np.asarray(out_flash), np.asarray(out_dense), rtol=2e-2, atol=2e-2
        )
