"""The fused-InfoNCE train step produces the same trajectory as the
dense-logits train step (CPU interpret mode, multi-device mesh)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from moco_tpu.core import build_encoder, create_state, make_train_step, place_state
from moco_tpu.parallel import create_mesh, shard_batch
from moco_tpu.utils.config import DataConfig, MocoConfig, OptimConfig, TrainConfig
from moco_tpu.utils.schedules import build_optimizer


def _run_steps(fused: bool, n_steps: int = 2):
    n_data = 2
    config = TrainConfig(
        moco=MocoConfig(
            arch="resnet18",
            dim=16,
            num_negatives=64,
            temperature=0.2,
            mlp=True,
            shuffle="gather_perm",
            cifar_stem=True,
            compute_dtype="float32",
            fused_infonce=fused,
            # block_k=32 with K=64 → the REAL pallas kernel (interpret
            # mode, 2-tile grid) runs inside the train step, not the
            # dense fallback infonce_stats would take at K < block.
            fused_block_k=32,
        ),
        optim=OptimConfig(lr=0.05, epochs=2, cos=True),
        data=DataConfig(dataset="synthetic", image_size=16, global_batch=8),
    )
    mesh = create_mesh(num_data=n_data, num_model=1, devices=jax.devices()[:n_data])
    encoder = build_encoder(config.moco, num_data=n_data)
    tx = build_optimizer(config.optim, steps_per_epoch=4)
    state = create_state(
        jax.random.PRNGKey(0), config, encoder, tx, jnp.zeros((1, 16, 16, 3))
    )
    state = place_state(state, mesh)
    step = make_train_step(config, encoder, tx, mesh)
    rng = jax.device_put(
        jax.random.PRNGKey(3), jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec())
    )
    metrics_hist = []
    for i in range(n_steps):
        ims = jax.random.normal(jax.random.PRNGKey(10 + i), (2, 8, 16, 16, 3))
        batch = shard_batch(mesh, {"im_q": ims[0], "im_k": ims[1]})
        state, metrics = step(state, batch, rng)
        # metrics now carry non-scalar health gauges too (queue_age_hist)
        metrics_hist.append({k: np.asarray(v) for k, v in metrics.items()})
    return state, metrics_hist


def test_fused_step_matches_dense_step():
    # fused_infonce=True on CPU runs the pallas kernel in interpret mode
    # over a 2-tile grid (fused_block_k=32, K=64)
    state_f, hist_f = _run_steps(fused=True)
    state_d, hist_d = _run_steps(fused=False)
    for mf, md in zip(hist_f, hist_d):
        np.testing.assert_allclose(mf["loss"], md["loss"], rtol=1e-5)
        np.testing.assert_allclose(mf["acc1"], md["acc1"], atol=1e-6)
        np.testing.assert_allclose(mf["acc5"], md["acc5"], atol=1e-6)
        # the health gauges are path-independent by construction (same
        # q/k/queue inputs on both sides) — they must agree too
        np.testing.assert_allclose(mf["logit_pos_mean"], md["logit_pos_mean"], rtol=1e-5)
        np.testing.assert_allclose(mf["queue_age_hist"], md["queue_age_hist"], atol=0)
    for a, b in zip(jax.tree.leaves(state_f.params_q), jax.tree.leaves(state_d.params_q)):
        # Tolerances calibrated to fp32 reassociation, not kernel bugs:
        # the fused kernel and the dense path reduce the queue axis in
        # different orders, and XLA:CPU's own reduction order varies
        # across jax releases. Two momentum-SGD steps at lr=0.05 amplify
        # that to a few 1e-4 absolute on a handful of elements (weight
        # scale ~5e-2), while the losses above still agree to rtol 1e-5.
        # A genuinely wrong gradient moves params at the 1e-2 scale.
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-3, atol=5e-4)
