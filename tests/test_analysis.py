"""mocolint: every rule proven on paired known-bad/known-good fixtures
(exact rule ids AND line numbers), suppression comments, CLI/JSON
surface, the repo-wide self-check, and the runtime arm (compile-miss
counter + recompile guard + strict-tracing driver smoke).

Fixtures under tests/fixtures/lint/ are parsed by the analyzer, never
imported: each `# expect: JXnnn` trailing comment marks a line that must
produce exactly one finding of that rule.
"""

import dataclasses
import json
import os
import re

import jax
import jax.numpy as jnp
import pytest

from moco_tpu.analysis import analyze_paths, analyze_source, iter_rules
from moco_tpu.analysis.__main__ import main as mocolint_main
from moco_tpu.analysis.runtime import CompileMonitor, RecompileGuard

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)), "fixtures", "lint")
ALL_RULES = (
    "JX001", "JX002", "JX003", "JX004", "JX005", "JX006", "JX007",
    "JX008", "JX009", "JX010", "JX011", "JX012", "JX013", "JX014",
    "JX015", "JX016", "JX017", "JX018",
)

_EXPECT_RE = re.compile(r"#\s*expect:\s*([A-Z0-9,\s]+)")


def _expected_lines(path: str, rule: str) -> set[int]:
    out = set()
    with open(path) as fh:
        for lineno, line in enumerate(fh, start=1):
            m = _EXPECT_RE.search(line)
            if m and rule in {t.strip() for t in m.group(1).split(",")}:
                out.add(lineno)
    return out


def _fixture(rule: str, kind: str) -> str:
    return os.path.join(FIXTURES, f"{rule.lower()}_{kind}.py")


# ---------------------------------------------------------------------------
# static rules


def test_all_rules_registered():
    assert [rid for rid, _ in iter_rules()] == list(ALL_RULES)


@pytest.mark.parametrize("rule", ALL_RULES)
def test_rule_fires_on_bad_fixture(rule):
    """Exact rule ids and line numbers on the known-bad snippet."""
    path = _fixture(rule, "bad")
    expected = _expected_lines(path, rule)
    assert expected, f"fixture {path} carries no expectations"
    findings = analyze_paths([path], rules=[rule])
    assert {f.line for f in findings} == expected
    assert all(f.rule == rule and not f.suppressed for f in findings)


@pytest.mark.parametrize("rule", ALL_RULES)
def test_rule_quiet_on_good_fixture(rule):
    """The paired known-good snippet is clean under EVERY rule — the
    false-positive guard for the idiomatic patterns."""
    findings = analyze_paths([_fixture(rule, "good")])
    assert findings == []


@pytest.mark.parametrize("rule", ALL_RULES)
def test_suppression_comment_mutes_rule(rule):
    """Appending `# mocolint: disable=<rule>` to each flagged line turns
    every finding into a suppressed one (and flips the exit semantics)."""
    path = _fixture(rule, "bad")
    expected = _expected_lines(path, rule)
    with open(path) as fh:
        lines = fh.read().splitlines()
    for lineno in expected:
        lines[lineno - 1] += f"  # mocolint: disable={rule}"
    findings = analyze_source("\n".join(lines), path, rules=[rule])
    assert {f.line for f in findings} == expected
    assert all(f.suppressed for f in findings)


def test_disable_all_token():
    src = "import time\nimport jax\n\n@jax.jit\ndef f(x):\n    t = time.time()  # mocolint: disable=all\n    return x + t\n"
    findings = analyze_source(src, "inline.py")
    assert findings and all(f.suppressed for f in findings)


def test_unrelated_suppression_does_not_mute():
    src = "import time\nimport jax\n\n@jax.jit\ndef f(x):\n    t = time.time()  # mocolint: disable=JX007\n    return x + t\n"
    findings = analyze_source(src, "inline.py", rules=["JX001"])
    assert findings and not any(f.suppressed for f in findings)


def test_syntax_error_is_reported_not_raised():
    findings = analyze_source("def broken(:\n", "broken.py")
    assert [f.rule for f in findings] == ["PARSE"]


def test_self_check_repo_is_lint_clean():
    """The acceptance bar: mocolint over the shipped tree reports zero
    unsuppressed findings (intentional patterns carry justified
    `# mocolint: disable=` comments)."""
    paths = [
        os.path.join(REPO, "moco_tpu"),
        os.path.join(REPO, "scripts"),
        os.path.join(REPO, "train.py"),
        os.path.join(REPO, "eval_lincls.py"),
        os.path.join(REPO, "bench.py"),
    ]
    bad = [f for f in analyze_paths(paths) if not f.suppressed]
    assert bad == [], "\n".join(f.render() for f in bad)


# ---------------------------------------------------------------------------
# CLI surface


def test_cli_exit_codes_and_json(tmp_path, capsys):
    report_path = tmp_path / "report.json"
    rc = mocolint_main(
        [_fixture("JX001", "bad"), "--no-baseline",
         "--format", "json", "-o", str(report_path)]
    )
    assert rc == 1
    report = json.loads(report_path.read_text())
    assert report["counts"]["active"] == len(_expected_lines(_fixture("JX001", "bad"), "JX001"))
    assert all(f["rule"] == "JX001" for f in report["findings"])
    capsys.readouterr()

    assert mocolint_main([_fixture("JX001", "good"), "--no-baseline"]) == 0
    assert "0 finding(s)" in capsys.readouterr().out


def test_cli_list_rules(capsys):
    assert mocolint_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rule in ALL_RULES:
        assert rule in out


def test_cli_rejects_unknown_rule(capsys):
    assert mocolint_main([_fixture("JX001", "bad"), "--rules", "JX999"]) == 2


def test_self_check_tests_tree_is_baseline_clean():
    """The acceptance command includes tests/ — every fixture finding is
    fingerprinted in the checked-in baseline, so the full run exits 0
    while a NEW finding would still fail. Analyzed at the SAME scope the
    baseline was generated at (interprocedural summaries are
    scope-dependent: a helper resolved in the full program can prove a
    pattern safe that looks risky in isolation)."""
    from moco_tpu.analysis.engine import load_baseline

    baseline = load_baseline(os.path.join(REPO, "mocolint-baseline.json"))
    assert baseline, "checked-in baseline is empty"
    paths = [
        os.path.join(REPO, "moco_tpu"),
        os.path.join(REPO, "scripts"),
        os.path.join(REPO, "tests"),
        os.path.join(REPO, "train.py"),
        os.path.join(REPO, "eval_lincls.py"),
        os.path.join(REPO, "bench.py"),
        os.path.join(REPO, "convert_pretrain.py"),
        os.path.join(REPO, "import_pretrain.py"),
    ]
    findings = analyze_paths(paths, baseline=baseline)
    fresh = [f for f in findings if f.active]
    assert fresh == [], "\n".join(f.render() for f in fresh)


# ---------------------------------------------------------------------------
# runtime arm


def test_compile_monitor_counts_retraces():
    @jax.jit
    def f(x):
        return x * 2

    mon = CompileMonitor(f)
    f(jnp.ones((4,)))
    first = mon.misses()
    assert first >= 1
    f(jnp.ones((4,)))  # cache hit: same shape
    assert mon.misses() == first
    f(jnp.ones((8,)))  # new shape: retrace
    assert mon.misses() == first + 1


def test_recompile_guard_aborts_only_after_warmup():
    guard = RecompileGuard(warmup_steps=8)
    assert guard.update(2, 1) is None
    assert guard.update(8, 3) is None  # warm-up compiles are free
    assert guard.update(16, 3) is None  # stable: healthy
    diagnosis = guard.update(24, 4)
    assert diagnosis is not None and "recompiled after warm-up" in diagnosis


def test_config_carries_strict_tracing_fields():
    from moco_tpu.utils.config import TrainConfig, config_from_dict, config_to_dict

    cfg = dataclasses.replace(
        TrainConfig(), strict_tracing=True, recompile_warmup_steps=3
    )
    rt = config_from_dict(config_to_dict(cfg))
    assert rt.strict_tracing is True
    assert rt.recompile_warmup_steps == 3


@pytest.mark.slow
def test_train_strict_tracing_smoke(tmp_path):
    """Driver smoke under --strict-tracing: every log line carries
    compile_cache_misses and the count is stable after warm-up (no
    recompiles) — the acceptance criterion, in miniature."""
    from moco_tpu.data.datasets import SyntheticDataset
    from moco_tpu.train import train
    from moco_tpu.utils.config import (
        DataConfig,
        MocoConfig,
        OptimConfig,
        TrainConfig,
    )

    config = TrainConfig(
        moco=MocoConfig(
            arch="resnet18", dim=16, num_negatives=64, mlp=True,
            shuffle="gather_perm", cifar_stem=True, compute_dtype="float32",
        ),
        optim=OptimConfig(lr=0.03, epochs=2, cos=True),
        data=DataConfig(dataset="synthetic", image_size=16, global_batch=16),
        workdir=str(tmp_path),
        log_every=1,
        strict_tracing=True,
        recompile_warmup_steps=2,
    )
    dataset = SyntheticDataset(num_examples=64, image_size=16)
    result = train(config, dataset=dataset)
    assert result["epoch"] == 1

    lines = [
        json.loads(l) for l in open(os.path.join(str(tmp_path), "metrics.jsonl"))
    ]
    logged = [l for l in lines if "compile_cache_misses" in l]
    assert logged, "strict tracing must surface compile_cache_misses"
    post_warmup = [
        l["compile_cache_misses"] for l in logged if l["step"] > config.recompile_warmup_steps
    ]
    assert post_warmup and len(set(post_warmup)) == 1, (
        f"recompiles after warm-up: {post_warmup}"
    )
