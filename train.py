#!/usr/bin/env python
"""CLI for MoCo pretraining — flag-compatible spirit of `main_moco.py:~L30-100`.

Usage:
    python train.py --preset cifar_smoke --data-dir /data/cifar10
    python train.py --arch resnet50 --mlp --aug-plus --cos --moco-t 0.2 \
        --lr 0.03 --batch-size 256 --epochs 200 --data imagefolder \
        --data-dir /data/imagenet --workdir /tmp/moco

The reference's distribution flags (`--world-size --rank --dist-url
--dist-backend --gpu --multiprocessing-distributed`) are intentionally
gone: the device mesh replaces the process-group world (SURVEY.md §2.4);
`--num-model` shards the negative queue for very large K.
"""

from __future__ import annotations

import argparse
import dataclasses

from moco_tpu.models import ARCHS
from moco_tpu.utils.config import (
    DataConfig,
    MocoConfig,
    OptimConfig,
    ParallelConfig,
    PRESETS,
    TrainConfig,
)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="MoCo TPU pretraining")
    p.add_argument("--preset", choices=sorted(PRESETS), default=None)
    # model (reference: --arch, --moco-dim/k/m/t, --mlp)
    p.add_argument("--arch", "-a", choices=ARCHS + ("vit_s16", "vit_b16", "vit_l16"), default=None)
    p.add_argument("--moco-dim", type=int, default=None)
    p.add_argument("--moco-k", type=int, default=None)
    p.add_argument("--moco-m", type=float, default=None)
    p.add_argument("--moco-t", type=float, default=None)
    p.add_argument("--mlp", action="store_true", default=None)
    p.add_argument(
        "--shuffle",
        choices=("gather_perm", "a2a", "syncbn", "none"),
        default=None,
        help="BN-decorrelation strategy (reference Shuffle-BN == gather_perm)",
    )
    p.add_argument(
        "--bn-stats-rows", type=int, default=None,
        help="BN training statistics from the first N rows per device "
        "(0 = full batch); byte-reduction lever matching the reference's "
        "32-row per-GPU statistics granularity",
    )
    p.add_argument(
        "--bn-stats-barrier", action="store_true", default=None,
        help="with --bn-stats-rows: fusion barrier around the subset "
        "slice (candidate workaround for the TPU compile pathology, "
        "see PROFILE.md / scripts/bn_compile_repro.py)",
    )
    p.add_argument(
        "--bn-momentum-stats", action="store_true", default=None,
        help="momentum-statistics BN (Momentum² Teacher, arXiv:2101.07525): "
        "normalize with the EMA-updated running statistics each train step "
        "instead of the raw batch moments — the large-batch alternative to "
        "cross-replica BN statistics (excludes --bn-stats-rows/--bn-virtual-groups)",
    )
    p.add_argument(
        "--bn-virtual-groups", type=int, default=None,
        help="virtual Shuffle-BN: per-group BN statistics over G row-groups "
        "+ in-batch key permutation — the reference's G-GPU recipe on one chip",
    )
    p.add_argument(
        "--key-bn-eval", dest="key_bn_running_stats", action="store_true",
        default=None,
        help="EMAN-style key forward: eval-mode BN from EMA'd running "
        "statistics — drops the key-side BN stats pass and the Shuffle-BN "
        "collectives (requires --shuffle none or syncbn). EXPERIMENTAL: "
        "measured accuracy arms trail Shuffle-BN at every tested budget "
        "(REPORT.md 'EMAN key forward')",
    )
    p.add_argument(
        "--no-key-bn-stats-warmup", dest="key_bn_stats_warmup",
        action="store_false", default=None,
        help="disable the key-stats EMA fast-tracking warmup schedule "
        "(on by default with --key-bn-eval) — reproduces the r4 "
        "no-warmup EMAN arm exactly",
    )
    # ViT options (moco-v3 family)
    p.add_argument(
        "--v3", action="store_true", default=None,
        help="MoCo v3: symmetric queue-free loss + prediction head (set --moco-k 0)",
    )
    p.add_argument(
        "--moco-m-cos", action="store_true", default=None,
        help="cosine-ramp the EMA momentum to 1.0 over training (v3 recipe)",
    )
    p.add_argument("--vit-pool", choices=("cls", "gap"), default=None)
    p.add_argument(
        "--vit-flash-attention", action="store_true", default=None,
        help="ViT attention via the Pallas flash kernel",
    )
    p.add_argument(
        "--vit-sequence-parallel", action="store_true", default=None,
        help="shard ViT tokens over the model axis (ring attention); needs --vit-pool gap",
    )
    p.add_argument(
        "--remat", action="store_true", default=None,
        help="rematerialize the query forward in backward (less HBM, ~30%% more FLOPs)",
    )
    # optim (reference: --lr --momentum --wd --schedule --cos --epochs)
    p.add_argument("--optimizer", choices=("sgd", "lars", "adamw"), default=None)
    p.add_argument("--lr", type=float, default=None)
    p.add_argument("--momentum", type=float, default=None)
    p.add_argument("--wd", "--weight-decay", dest="wd", type=float, default=None)
    p.add_argument("--schedule", type=int, nargs="*", default=None)
    p.add_argument("--cos", action="store_true", default=None)
    p.add_argument("--warmup-epochs", type=int, default=None)
    p.add_argument("--epochs", type=int, default=None)
    # data (reference: positional DATA, --batch-size, --aug-plus, --workers)
    p.add_argument("--data", dest="dataset", choices=("synthetic", "synthetic_learnable", "synthetic_hard", "cifar10", "imagefolder"), default=None)
    p.add_argument("--data-dir", default=None)
    p.add_argument("--image-size", type=int, default=None)
    p.add_argument("--batch-size", "-b", type=int, default=None)
    p.add_argument("--aug-plus", action="store_true", default=None)
    p.add_argument("--workers", "-j", type=int, default=None)
    p.add_argument(
        "--no-host-rrc", dest="host_rrc", action="store_false", default=None,
        help="disable host-side exact RandomResizedCrop (fall back to canvas decode + on-device crop)",
    )
    p.add_argument(
        "--cache-dir", default=None,
        help="decode-once packed RGB cache dir: build on first use, then "
        "epochs read raw pixels from an mmap instead of re-decoding JPEGs",
    )
    p.add_argument(
        "--knn-every-epochs", type=int, default=None,
        help="periodic frozen-feature kNN monitor (0 = off)",
    )
    p.add_argument(
        "--checkpoint-async", action="store_true", default=None,
        help="overlap checkpoint writes with training (Orbax async); the "
        "preemption save still blocks until durable",
    )
    p.add_argument(
        "--keep", type=int, default=None,
        help="retain the last N checkpoints (default 3); 0 keeps every "
        "one — the reference's per-epoch retention (main_moco.py:~L275-280)",
    )
    # fault tolerance (robustness layer)
    p.add_argument(
        "--watchdog-timeout", type=float, default=None,
        help="seconds without a completed step before the stall watchdog "
        "dumps all-thread stacks, writes an emergency checkpoint, and "
        "exits nonzero (0 = off; first step gets a compile grace period)",
    )
    p.add_argument(
        "--nan-guard-threshold", type=int, default=None,
        help="abort after this many non-finite-loss log steps (each one "
        "is skipped + counted in metrics.jsonl)",
    )
    p.add_argument(
        "--strict-tracing", action="store_true", default=None,
        help="mocolint runtime arm: enable jax.check_tracer_leaks, report "
        "compile_cache_misses on every metrics.jsonl log line, and abort "
        "if the step function recompiles after the warm-up window",
    )
    p.add_argument(
        "--recompile-warmup", type=int, default=None,
        help="with --strict-tracing: steps during which compiles are free "
        "(first trace); a compile-cache miss after this aborts (default 8)",
    )
    p.add_argument(
        "--sanitize-collectives", action="store_true", default=None,
        help="mocolint runtime arm: record every comms-tagged collective "
        "site's (site, kind, operand-shape) schedule, publish its hash "
        "out-of-band on log steps (schedule.p<i>.json), cross-check "
        "against every peer process, and abort with a per-site diff on "
        "divergence — BEFORE the pod deadlocks in the mismatched "
        "collective",
    )
    p.add_argument(
        "--sanitize-threads", action="store_true", default=None,
        help="mocolint v3 runtime arm: trace every tsan-factory lock's "
        "acquisition order per thread, abort with both stacks "
        "(lock_order_diff.json) the moment two paths disagree on the "
        "nesting — BEFORE the deadlock wedges the process; blocking ops "
        "under a held lock land in the run report (lock_order.json). "
        "Smoke-run tooling: the profile hook costs real CPU",
    )
    p.add_argument(
        "--elastic", action="store_true", default=None,
        help="elastic training (parallel/elastic.py): on heartbeat loss "
        "the survivors agree on the event, take an emergency checkpoint, "
        "rebuild a smaller mesh, reshard params/optimizer/queue onto it, "
        "re-derive momentum/LR from the shrunk global batch (m^kappa / "
        "linear), and resume in-process — no restart from scratch "
        "(requires --num-model 1)",
    )
    p.add_argument(
        "--heartbeat-timeout", type=float, default=None,
        help="heartbeat-staleness threshold in seconds for declaring a "
        "host lost (the alert engine's heartbeat_loss rule AND the "
        "elastic rescale trigger; default 120). Must exceed the "
        "worst-case wall time between log steps",
    )
    p.add_argument(
        "--auto-scale", default=None, metavar="ref_batch=N",
        help="principled batch scaling (arXiv:2307.13813): treat --lr "
        "and --moco-m as reference values at global batch N and derive "
        "the live values from the actual batch (kappa = batch/N: lr "
        "linear, EMA momentum m^kappa). Elastic runs default this to "
        "the original batch so a rescale re-derives against it",
    )
    p.add_argument(
        "--faults", default=None,
        help="deterministic fault-injection spec (chaos testing), e.g. "
        "'ckpt_truncate@step=8,io@site=data.read:at=3,nan@step=6' — "
        "same grammar as the MOCO_FAULTS env var",
    )
    # parallel / infra
    p.add_argument("--num-data", type=int, default=None, help="data-axis size (default: all devices)")
    p.add_argument("--num-model", type=int, default=None, help="model-axis size (shards the queue)")
    p.add_argument(
        "--shard-weight-update", action="store_true", default=None,
        help="ZeRO: shard optimizer state + weight update over the data axis (sgd/adamw)",
    )
    p.add_argument(
        "--zero-stage", type=int, default=None, choices=(1, 2, 3),
        help="with --shard-weight-update: 1 = sharded opt state only; "
        "2/3 = params also persist as P(data) shards with bucketed, "
        "driver-overlapped collectives (parallel/zero.py)",
    )
    p.add_argument(
        "--zero-bucket-mb", type=float, default=None,
        help="ZeRO-2/3 fusion-bucket size (MB of shard payload per collective)",
    )
    p.add_argument(
        "--no-zero-overlap-gather", dest="zero_overlap_gather",
        action="store_false", default=None,
        help="run the ZeRO-2/3 params gather inline instead of hoisted "
        "under the previous step (A/B lever)",
    )
    p.add_argument(
        "--zero-layer-granular", action="store_true", default=None,
        help="with --zero-stage 2/3: gather each layer group's full "
        "params just-in-time (one-group-ahead prefetch) and free them "
        "after the group's forward/backward — peak model memory drops "
        "from the whole tree to shards + one live group",
    )
    p.add_argument("--seed", type=int, default=None)
    p.add_argument("--workdir", default=None)
    p.add_argument("--print-freq", "-p", type=int, default=None)
    p.add_argument("--steps-per-epoch", type=int, default=None, help="override (smoke tests)")
    p.add_argument("--profile-dir", default=None, help="jax.profiler trace output dir")
    # telemetry (moco_tpu/obs)
    p.add_argument(
        "--profile-steps", default=None, metavar="A:B",
        help="capture the jax.profiler trace for global steps [A, B) only "
        "(into --profile-dir or workdir/profile) instead of the whole run; "
        "intended for real-chip runs (jax's CPU backend can deadlock on "
        "mid-run profiler starts)",
    )
    p.add_argument(
        "--sinks", default=None,
        help="comma list of metric sinks (jsonl,csv,tensorboard); the "
        "JSONL sink is always included",
    )
    p.add_argument(
        "--metrics-port", type=int, default=None,
        help="serve Prometheus text format on http://HOST:(PORT + "
        "process_index)/metrics (0 = off) for scraping long runs; the "
        "per-process shift keeps co-hosted processes from colliding",
    )
    p.add_argument(
        "--metrics-host", default=None,
        help="bind address for the Prometheus endpoint (default "
        "127.0.0.1; use 0.0.0.0 for off-box scrapers)",
    )
    p.add_argument(
        "--no-fleet-metrics", dest="fleet_metrics", action="store_false",
        default=None,
        help="disable cross-host fleet aggregation (fleet min/mean/max/"
        "argmax + straggler_skew on process-0 metrics lines) and the "
        "per-host heartbeat files",
    )
    p.add_argument(
        "--alert-rules", default=None,
        help="in-stream alert rules (moco_tpu/obs/alerts.py grammar): "
        "'default' = built-ins (step-time spike, data starvation, "
        "straggler skew, EMA runaway, queue staleness, non-finite loss, "
        "stall, heartbeat loss); 'default,<spec>' extends; 'none' off",
    )
    p.add_argument(
        "--alerts-fatal", action="store_true", default=None,
        help="abort the run on any fired alert, after an emergency "
        "checkpoint (reuses the fault-tolerance save-first path)",
    )
    p.add_argument(
        "--obs-probe-every", type=int, default=None,
        help="step-time breakdown probe: every N steps block_until_ready "
        "the step to split host dispatch from device compute "
        "(t_dispatch/t_device on metric lines; 0 disables sampling)",
    )
    p.add_argument(
        "--no-health-metrics", dest="health_metrics", action="store_false",
        default=None,
        help="disable the in-step MoCo health gauges (EMA drift, logit "
        "stats, collapse detection, queue staleness)",
    )
    p.add_argument(
        "--no-device-prefetch", dest="device_prefetch", action="store_false",
        default=None,
        help="disable the device prefetch ring (data/device_prefetch.py) "
        "and fall back to the synchronous input path — decode, host→"
        "device transfer, and compute take turns instead of overlapping",
    )
    p.add_argument(
        "--prefetch-depth", type=int, default=None,
        help="device prefetch ring depth: batches staged on device ahead "
        "of the step loop, and the in-flight step window (default 2; "
        "raise on hosts whose wire is bursty, at ~2 batch-pairs of HBM "
        "per slot)",
    )
    p.add_argument(
        "--prefetch-donate", action="store_true", default=None,
        help="donate the consumed staging slot's uint8 buffer to the "
        "augment step (XLA reuses its HBM for the normalized output); "
        "ignored on backends without donation",
    )
    return p


def config_from_args(args: argparse.Namespace) -> TrainConfig:
    cfg = PRESETS[args.preset] if args.preset else TrainConfig()

    def override(dc, **kv):
        kv = {k: v for k, v in kv.items() if v is not None}
        return dataclasses.replace(dc, **kv) if kv else dc

    moco = override(
        cfg.moco,
        arch=args.arch,
        dim=args.moco_dim,
        num_negatives=args.moco_k,
        momentum=args.moco_m,
        temperature=args.moco_t,
        mlp=args.mlp,
        shuffle=args.shuffle,
        bn_stats_rows=args.bn_stats_rows,
        bn_stats_barrier=args.bn_stats_barrier,
        bn_momentum_stats=args.bn_momentum_stats,
        bn_virtual_groups=args.bn_virtual_groups,
        key_bn_running_stats=args.key_bn_running_stats,
        key_bn_stats_warmup=args.key_bn_stats_warmup,
        v3=args.v3,
        momentum_cos=args.moco_m_cos,
        vit_pool=args.vit_pool,
        vit_flash_attention=args.vit_flash_attention,
        vit_sequence_parallel=args.vit_sequence_parallel,
        remat=args.remat,
    )
    optim = override(
        cfg.optim,
        optimizer=args.optimizer,
        lr=args.lr,
        momentum=args.momentum,
        weight_decay=args.wd,
        schedule=tuple(args.schedule) if args.schedule is not None else None,
        cos=args.cos,
        warmup_epochs=args.warmup_epochs,
        epochs=args.epochs,
    )
    data = override(
        cfg.data,
        dataset=args.dataset,
        data_dir=args.data_dir,
        image_size=args.image_size,
        global_batch=args.batch_size,
        aug_plus=args.aug_plus,
        num_workers=args.workers,
        host_rrc=args.host_rrc,
        cache_dir=args.cache_dir,
    )
    parallel = override(
        cfg.parallel,
        num_data=args.num_data,
        num_model=args.num_model,
        shard_weight_update=args.shard_weight_update,
        zero_stage=args.zero_stage,
        zero_bucket_mb=args.zero_bucket_mb,
        zero_overlap_gather=args.zero_overlap_gather,
        zero_layer_granular=args.zero_layer_granular,
    )
    return override(
        dataclasses.replace(cfg, moco=moco, optim=optim, data=data, parallel=parallel),
        seed=args.seed,
        workdir=args.workdir,
        log_every=args.print_freq,
        steps_per_epoch=args.steps_per_epoch,
        knn_every_epochs=args.knn_every_epochs,
        checkpoint_async=args.checkpoint_async,
        checkpoint_keep=args.keep,
        watchdog_timeout=args.watchdog_timeout,
        nan_guard_threshold=args.nan_guard_threshold,
        strict_tracing=args.strict_tracing,
        recompile_warmup_steps=args.recompile_warmup,
        sanitize_collectives=args.sanitize_collectives,
        sanitize_threads=args.sanitize_threads,
        sinks=args.sinks,
        metrics_port=args.metrics_port,
        metrics_host=args.metrics_host,
        health_metrics=args.health_metrics,
        obs_probe_every=args.obs_probe_every,
        fleet_metrics=args.fleet_metrics,
        alert_rules=args.alert_rules,
        alerts_fatal=args.alerts_fatal,
        elastic=args.elastic,
        heartbeat_timeout=args.heartbeat_timeout,
        auto_scale=args.auto_scale,
        device_prefetch=args.device_prefetch,
        prefetch_depth=args.prefetch_depth,
        prefetch_donate=args.prefetch_donate,
    )


def main() -> None:
    args = build_parser().parse_args()
    from moco_tpu.utils.platform import enable_persistent_compilation_cache, pin_platform_from_env

    pin_platform_from_env()
    enable_persistent_compilation_cache()
    if args.faults:
        from moco_tpu.utils import faults

        faults.install(args.faults)
    config = config_from_args(args)
    profile_steps = None
    if args.profile_steps:
        from moco_tpu.utils.metrics import parse_profile_steps

        profile_steps = parse_profile_steps(args.profile_steps)
    from moco_tpu.train import train

    result = train(config, profile_dir=args.profile_dir, profile_steps=profile_steps)
    print(f"done: {result}")


if __name__ == "__main__":
    main()
